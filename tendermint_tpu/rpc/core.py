"""RPC route handlers (reference: rpc/core/*.go, routes at
rpc/core/routes.go:12-48). JSON result shapes follow the reference's
response types (amino-style JSON: hex upper-case hashes, stringified ints).
"""

from __future__ import annotations

import base64
import os
import threading
import time as _time

from tendermint_tpu.abci import types as abci
from tendermint_tpu.types import events as tmevents
from tendermint_tpu.types.tx import tx_hash


class Environment:
    """reference: rpc/core/env.go Environment."""

    def __init__(self, node):
        self.node = node
        self.event_bus = node.event_bus


class ErrOverloaded(Exception):
    """Typed overload verdict from the broadcast_tx admission gate
    (docs/OVERLOAD.md): the node is shedding RPC tx load instead of
    queuing it unboundedly. Clients should back off and retry."""


class _TxAdmissionGate:
    """Max-inflight admission for broadcast_tx_* (no reference analogue —
    the reference lets handler goroutines pile up on the mempool lock).
    One per node; a slot is held for the duration of the CheckTx, the
    part that contends on the mempool + ABCI connection."""

    def __init__(self, limit: int):
        self.limit = limit
        self._inflight = 0
        self._mtx = threading.Lock()

    def acquire(self, board=None) -> None:
        if self.limit <= 0:
            return
        with self._mtx:
            if self._inflight >= self.limit:
                if board is not None:
                    board.count_shed("rpc_tx")
                self._count_shed_metric()
                raise ErrOverloaded(
                    f"node overloaded: {self._inflight} broadcast_tx "
                    f"requests in flight (limit {self.limit}); retry later")
            self._inflight += 1

    @staticmethod
    def _count_shed_metric() -> None:
        """The ingest shed/reject split (docs/INGEST.md): gate sheds land
        in the pre-seeded ingest_txs_total{result="shed"} counter next to
        the batch path's ok/reject tallies."""
        try:
            from tendermint_tpu.utils import metrics as tmmetrics

            m = tmmetrics.GLOBAL_NODE_METRICS
            if m is not None:
                m.ingest_txs.add(1, result="shed")
        except Exception:  # noqa: BLE001 - metrics never block shedding
            pass

    def release(self) -> None:
        if self.limit <= 0:
            return
        with self._mtx:
            self._inflight = max(0, self._inflight - 1)


_GATE_CREATE_MTX = threading.Lock()


def _tx_gate(env) -> _TxAdmissionGate:
    gate = getattr(env.node, "_rpc_tx_gate", None)
    if gate is None:
        with _GATE_CREATE_MTX:
            gate = getattr(env.node, "_rpc_tx_gate", None)
            if gate is None:
                cfg = getattr(getattr(env.node, "config", None), "rpc", None)
                limit = getattr(cfg, "max_broadcast_tx_inflight", 0) if cfg else 0
                gate = _TxAdmissionGate(limit)
                env.node._rpc_tx_gate = gate
    return gate


def _node_scoreboard(env):
    return getattr(getattr(env.node, "switch", None), "scoreboard", None)


def _mempool_submit(env, raw: bytes):
    """Route a broadcast_tx through the micro-batched ingest front door
    (docs/INGEST.md) when the mempool has one: concurrent handler threads
    share batched CheckTx dispatches while each still holds its own
    admission-gate slot. Falls back to plain check_tx for mempool fakes."""
    mp = env.node.mempool
    fn = getattr(mp, "ingest_tx", None)
    if fn is None:
        return mp.check_tx(raw)
    return fn(raw)


def _b64(b: bytes) -> str:
    return base64.b64encode(b or b"").decode()


def _hex(b: bytes) -> str:
    return (b or b"").hex().upper()


def _block_id_json(bid) -> dict:
    return {
        "hash": _hex(bid.hash),
        "parts": {"total": bid.part_set_header.total, "hash": _hex(bid.part_set_header.hash)},
    }


def _header_json(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": str(h.time),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hex(h.last_commit_hash),
        "data_hash": _hex(h.data_hash),
        "validators_hash": _hex(h.validators_hash),
        "next_validators_hash": _hex(h.next_validators_hash),
        "consensus_hash": _hex(h.consensus_hash),
        "app_hash": _hex(h.app_hash),
        "last_results_hash": _hex(h.last_results_hash),
        "evidence_hash": _hex(h.evidence_hash),
        "proposer_address": _hex(h.proposer_address),
    }


def _commit_json(c) -> dict:
    if c is None:
        return None
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": s.block_id_flag,
                "validator_address": _hex(s.validator_address),
                "timestamp": str(s.timestamp),
                "signature": _b64(s.signature),
            }
            for s in c.signatures
        ],
    }


def _evidence_json(ev) -> dict:
    """reference: types/evidence.go MarshalJSON shapes (subset)."""
    from tendermint_tpu.types.evidence import (
        DuplicateVoteEvidence, LightClientAttackEvidence)

    if isinstance(ev, DuplicateVoteEvidence):
        return {"type": "tendermint/DuplicateVoteEvidence", "value": {
            "vote_a": {"height": str(ev.vote_a.height),
                       "round": ev.vote_a.round,
                       "type": ev.vote_a.type,
                       "validator_address": _hex(ev.vote_a.validator_address),
                       "block_id": _block_id_json(ev.vote_a.block_id)},
            "vote_b": {"height": str(ev.vote_b.height),
                       "round": ev.vote_b.round,
                       "type": ev.vote_b.type,
                       "validator_address": _hex(ev.vote_b.validator_address),
                       "block_id": _block_id_json(ev.vote_b.block_id)},
            "total_voting_power": str(ev.total_voting_power),
            "validator_power": str(ev.validator_power),
            "timestamp": str(ev.timestamp),
        }}
    if isinstance(ev, LightClientAttackEvidence):
        return {"type": "tendermint/LightClientAttackEvidence", "value": {
            "common_height": str(ev.common_height),
            "total_voting_power": str(ev.total_voting_power),
            "timestamp": str(ev.timestamp),
        }}
    return {"type": type(ev).__name__, "value": {}}


def _block_json(b) -> dict:
    return {
        "header": _header_json(b.header),
        "data": {"txs": [_b64(t) for t in b.data.txs]},
        "evidence": {"evidence": [_evidence_json(e) for e in b.evidence]},
        "last_commit": _commit_json(b.last_commit),
    }


def encode_event_data(data) -> dict:
    """Event payloads for WS subscriptions."""
    if isinstance(data, tmevents.EventDataNewBlock):
        return {"type": "tendermint/event/NewBlock",
                "value": {"block": _block_json(data.block)}}
    if isinstance(data, tmevents.EventDataTx):
        return {"type": "tendermint/event/Tx", "value": {
            "TxResult": {"height": str(data.height), "index": data.index,
                         "tx": _b64(data.tx),
                         "result": {"code": data.result.code if data.result else 0}}}}
    if isinstance(data, tmevents.EventDataNewBlockHeader):
        return {"type": "tendermint/event/NewBlockHeader",
                "value": {"header": _header_json(data.header)}}
    if isinstance(data, tmevents.EventDataRoundState):
        return {"type": "tendermint/event/RoundState", "value": {
            "height": str(data.height), "round": data.round, "step": data.step}}
    if isinstance(data, tmevents.EventDataVote):
        return {"type": "tendermint/event/Vote", "value": {"vote": str(data.vote)}}
    return {"type": type(data).__name__, "value": {}}


# --- info routes (reference: rpc/core/routes.go) ----------------------------


def health(env):
    return {}


def status(env):
    node = env.node
    latest_height = node.block_store.height
    meta = node.block_store.load_block_meta(latest_height)
    earliest_meta = node.block_store.load_base_meta()
    pub = node.priv_validator.get_pub_key() if node.priv_validator else None
    return {
        "node_info": {
            "protocol_version": {"p2p": "8", "block": "11", "app": "0"},
            "id": node.node_key.id(),
            "listen_addr": node.transport.node_info.listen_addr,
            "network": node.genesis.chain_id,
            "version": "0.34.24-tpu",
            "channels": _hex(node.transport.node_info.channels),
            "moniker": node.config.base.moniker,
            "other": {
                "tx_index": ("on" if getattr(node, "tx_indexer", None)
                             is not None else "off"),
                "rpc_address": node.config.rpc.laddr,
            },
        },
        "sync_info": {
            "latest_block_hash": _hex(meta.block_id.hash) if meta else "",
            "latest_app_hash": _hex(meta.header.app_hash) if meta else "",
            "latest_block_height": str(latest_height),
            "latest_block_time": str(meta.header.time) if meta else "",
            "earliest_block_hash": (_hex(earliest_meta.block_id.hash)
                                    if earliest_meta else ""),
            "earliest_app_hash": (_hex(earliest_meta.header.app_hash)
                                  if earliest_meta else ""),
            "earliest_block_height": str(node.block_store.base),
            "earliest_block_time": str(earliest_meta.header.time) if earliest_meta else "",
            "catching_up": bool(getattr(node.consensus_reactor, "wait_sync", False)),
        },
        "validator_info": {
            "address": _hex(pub.address()) if pub else "",
            "pub_key": {"type": "tendermint/PubKeyEd25519", "value": _b64(pub.bytes())} if pub else None,
            "voting_power": "0",
        },
    }


def net_info(env):
    sw = env.node.switch
    with sw._peers_mtx:
        peers = list(sw.peers.values())
    return {
        "listening": True,
        "listeners": [env.node.transport.node_info.listen_addr],
        "n_peers": str(len(peers)),
        "peers": [
            {"node_info": {"id": p.id, "moniker": p.node_info.moniker},
             "is_outbound": p.outbound, "remote_ip": p.socket_addr}
            for p in peers
        ],
    }


def genesis(env):
    import json as _json

    return {"genesis": _json.loads(env.node.genesis.to_json())}


def genesis_chunked(env, chunk=0):
    data = env.node.genesis.to_json().encode()
    chunk_size = 16 * 1024 * 1024
    chunks = [data[i:i + chunk_size] for i in range(0, len(data), chunk_size)] or [b""]
    c = int(chunk)
    if c < 0 or c >= len(chunks):
        raise ValueError(f"there are {len(chunks)} chunks, but you requested {c}")
    return {"chunk": str(c), "total": str(len(chunks)), "data": _b64(chunks[c])}


def blockchain(env, minHeight=0, maxHeight=0):
    """reference: rpc/core/blocks.go BlockchainInfo."""
    store = env.node.block_store
    max_h = int(maxHeight) or store.height
    max_h = min(max_h, store.height)
    min_h = max(int(minHeight) or store.base, store.base)
    min_h = max(min_h, max_h - 19)
    metas = []
    for h in range(max_h, min_h - 1, -1):
        m = store.load_block_meta(h)
        if m is not None:
            metas.append({
                "block_id": _block_id_json(m.block_id),
                "block_size": str(m.block_size),
                "header": _header_json(m.header),
                "num_txs": str(m.num_txs),
            })
    return {"last_height": str(store.height), "block_metas": metas}


def block(env, height=0):
    store = env.node.block_store
    h = int(height) or store.height
    b = store.load_block(h)
    m = store.load_block_meta(h)
    if b is None:
        raise ValueError(f"could not find block at height {h}")
    return {"block_id": _block_id_json(m.block_id), "block": _block_json(b)}


def _parse_hash(hash: str) -> bytes:
    """A 32-byte hash arrives as 64 hex chars (URI style) or base64 (JSON
    style); 64 hex chars can't be valid base64 for 32 bytes, so length
    disambiguates."""
    if len(hash) == 64 and all(c in "0123456789abcdefABCDEF" for c in hash):
        return bytes.fromhex(hash)
    return base64.b64decode(hash)


def block_by_hash(env, hash=""):
    b = env.node.block_store.load_block_by_hash(_parse_hash(hash))
    if b is None:
        return {"block_id": None, "block": None}
    m = env.node.block_store.load_block_meta(b.header.height)
    return {"block_id": _block_id_json(m.block_id), "block": _block_json(b)}


def block_search(env, query="", page=1, per_page=30, order_by=""):
    """reference: rpc/core/blocks.go:113 BlockSearch (kv block indexer;
    empty order_by defaults to desc, anything else than asc/desc errors)."""
    indexer = getattr(env.node, "block_indexer", None)
    if indexer is None:
        raise ValueError("block indexing is disabled")
    heights = indexer.search(query)
    if order_by in ("desc", ""):
        heights = list(reversed(heights))
    elif order_by != "asc":
        raise ValueError("expected order_by to be either `asc` or `desc`")
    page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
    start = (page - 1) * per_page
    blocks = []
    for h in heights[start:start + per_page]:
        b = env.node.block_store.load_block(h)
        m = env.node.block_store.load_block_meta(h)
        if b is not None and m is not None:
            blocks.append({"block_id": _block_id_json(m.block_id),
                           "block": _block_json(b)})
    return {"blocks": blocks, "total_count": str(len(heights))}


def header(env, height=0):
    """reference: rpc/core/blocks.go:95 Header."""
    store = env.node.block_store
    h = int(height) or store.height
    m = store.load_block_meta(h)
    if m is None:
        raise ValueError(f"could not find header at height {h}")
    return {"header": _header_json(m.header)}


def header_by_hash(env, hash=""):
    """reference: rpc/core/blocks.go:105 HeaderByHash."""
    b = env.node.block_store.load_block_by_hash(_parse_hash(hash))
    if b is None:
        return {"header": None}
    return {"header": _header_json(b.header)}


def block_results(env, height=0):
    h = int(height) or env.node.block_store.height
    resp = env.node.state_store.load_abci_responses(h)
    return {
        "height": str(h),
        "txs_results": [
            {"code": r.code, "data": _b64(r.data), "log": r.log,
             "gas_wanted": str(r.gas_wanted), "gas_used": str(r.gas_used)}
            for r in resp.deliver_txs
        ],
        "begin_block_events": [],
        "end_block_events": [],
        "validator_updates": [],
        "consensus_param_updates": None,
    }


def commit(env, height=0):
    store = env.node.block_store
    h = int(height) or store.height
    m = store.load_block_meta(h)
    if m is None:
        raise ValueError(f"could not find block meta at height {h}")
    c = store.load_block_commit(h) or store.load_seen_commit(h)
    return {
        "signed_header": {"header": _header_json(m.header), "commit": _commit_json(c)},
        "canonical": store.load_block_commit(h) is not None,
    }


def validators(env, height=0, page=1, per_page=30):
    h = int(height) or env.node.block_store.height + 1
    vals = env.node.state_store.load_validators(h)
    page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
    start = (page - 1) * per_page
    sel = vals.validators[start:start + per_page]
    return {
        "block_height": str(h),
        "validators": [
            {"address": _hex(v.address),
             "pub_key": {"type": "tendermint/PubKeyEd25519", "value": _b64(v.pub_key.bytes())},
             "voting_power": str(v.voting_power),
             "proposer_priority": str(v.proposer_priority)}
            for v in sel
        ],
        "count": str(len(sel)),
        "total": str(vals.size()),
    }


def consensus_params(env, height=0):
    h = int(height) or env.node.block_store.height + 1
    params = env.node.state_store.load_consensus_params(h)
    return {
        "block_height": str(h),
        "consensus_params": {
            "block": {"max_bytes": str(params.block.max_bytes),
                      "max_gas": str(params.block.max_gas),
                      "time_iota_ms": str(params.block.time_iota_ms)},
            "evidence": {"max_age_num_blocks": str(params.evidence.max_age_num_blocks),
                         "max_age_duration": str(params.evidence.max_age_duration_ns),
                         "max_bytes": str(params.evidence.max_bytes)},
            "validator": {"pub_key_types": list(params.validator.pub_key_types)},
            "version": {"app_version": str(params.version.app_version)},
        },
    }


def consensus_state(env):
    rs = env.node.consensus.rs
    return {"round_state": {
        "height/round/step": f"{rs.height}/{rs.round}/{rs.step}",
        "height": str(rs.height), "round": rs.round, "step": rs.step,
        "start_time": str(rs.start_time),
        "proposal_block_hash": _hex(rs.proposal_block.hash()) if rs.proposal_block else "",
        "locked_block_hash": _hex(rs.locked_block.hash()) if rs.locked_block else "",
        "valid_block_hash": _hex(rs.valid_block.hash()) if rs.valid_block else "",
    }}


def dump_consensus_state(env):
    out = consensus_state(env)
    out["peers"] = [
        {"node_address": p.id,
         "peer_state": {"round_state": {
             "height": str(ps.prs.height), "round": ps.prs.round, "step": ps.prs.step}}}
        for p in env.node.switch.peers.values()
        for ps in [p.get("consensus_peer_state")] if ps is not None
    ]
    return out


def unconfirmed_txs(env, limit=30):
    txs = env.node.mempool.reap_max_txs(min(int(limit), 100))
    return {
        "n_txs": str(len(txs)),
        "total": str(env.node.mempool.size()),
        "total_bytes": str(env.node.mempool.size_bytes()),
        "txs": [_b64(t) for t in txs],
    }


def num_unconfirmed_txs(env):
    return {
        "n_txs": str(env.node.mempool.size()),
        "total": str(env.node.mempool.size()),
        "total_bytes": str(env.node.mempool.size_bytes()),
        "txs": None,
    }


# --- tx routes --------------------------------------------------------------


def _decode_tx_param(tx) -> bytes:
    if isinstance(tx, bytes):
        return tx
    return base64.b64decode(tx)


def broadcast_tx_async(env, tx):
    raw = _decode_tx_param(tx)
    # the admission slot is taken HERE (typed overload error to the
    # caller) and released by the worker thread after CheckTx: async
    # submission must not become an unbounded thread/mempool-queue bomb
    gate = _tx_gate(env)
    gate.acquire(_node_scoreboard(env))
    try:
        threading.Thread(target=_check_tx_quiet, args=(env, raw, gate),
                         daemon=True).start()
    except BaseException:
        # thread spawn failing (fd/thread exhaustion — exactly the
        # overload this gate guards) must not leak the slot forever
        gate.release()
        raise
    return {"code": 0, "data": "", "log": "", "codespace": "", "hash": _hex(tx_hash(raw))}


def _check_tx_quiet(env, raw, gate):
    try:
        _mempool_submit(env, raw)
    except Exception:  # noqa: BLE001
        pass
    finally:
        gate.release()


def broadcast_tx_sync(env, tx):
    raw = _decode_tx_param(tx)
    gate = _tx_gate(env)
    gate.acquire(_node_scoreboard(env))  # ErrOverloaded propagates, typed
    try:
        res = _mempool_submit(env, raw)
        return {"code": res.code, "data": _b64(res.data), "log": res.log,
                "codespace": res.codespace, "hash": _hex(tx_hash(raw))}
    except Exception as e:  # noqa: BLE001
        return {"code": 1, "data": "", "log": str(e), "codespace": "mempool",
                "hash": _hex(tx_hash(raw))}
    finally:
        gate.release()


def broadcast_tx_commit(env, tx):
    """Waits for the tx to be committed (reference: rpc/core/mempool.go:60)."""
    raw = _decode_tx_param(tx)
    # the admission verdict comes FIRST: a shed request must cost nothing —
    # subscribing before the gate would keep the event-bus lock and
    # subscriber map hot under exactly the overload the gate exists to
    # shed. The slot covers only the subscribe + CheckTx (the contended
    # part); holding it through the commit wait would starve the gate on
    # the block interval instead of on actual mempool pressure.
    gate = _tx_gate(env)
    gate.acquire(_node_scoreboard(env))
    q = tmevents.Query(f"{tmevents.EVENT_TYPE_KEY}='{tmevents.EVENT_TX}' AND "
                       f"{tmevents.TX_HASH_KEY}='{_hex(tx_hash(raw))}'")
    subscriber = f"btc-{_hex(tx_hash(raw))[:16]}"
    try:
        sub = env.event_bus.subscribe(subscriber, q)
    except BaseException:
        gate.release()
        raise
    try:
        try:
            check = _mempool_submit(env, raw)
        finally:
            gate.release()
        if not check.is_ok():
            return {"check_tx": {"code": check.code, "log": check.log},
                    "deliver_tx": {}, "hash": _hex(tx_hash(raw)), "height": "0"}
        deadline = _time.monotonic() + env.node.config.rpc.timeout_broadcast_tx_commit_s
        while _time.monotonic() < deadline:
            msg = sub.next(timeout=0.25)
            if msg is not None:
                data = msg.data
                return {
                    "check_tx": {"code": check.code, "log": check.log},
                    "deliver_tx": {"code": data.result.code, "log": data.result.log},
                    "hash": _hex(tx_hash(raw)),
                    "height": str(data.height),
                }
        raise TimeoutError("timed out waiting for tx to be included in a block")
    finally:
        try:
            env.event_bus.unsubscribe_all(subscriber)
        except ValueError:
            pass


def check_tx(env, tx):
    raw = _decode_tx_param(tx)
    res = env.node.proxy_app.mempool.check_tx(abci.RequestCheckTx(tx=raw))
    return {"code": res.code, "data": _b64(res.data), "log": res.log,
            "gas_wanted": str(res.gas_wanted), "gas_used": str(res.gas_used)}


def tx(env, hash="", prove=False):
    """Requires the kv indexer (reference: rpc/core/tx.go)."""
    raw = base64.b64decode(hash) if isinstance(hash, str) else hash
    indexer = getattr(env.node, "tx_indexer", None)
    if indexer is None:
        raise ValueError("transaction indexing is disabled")
    res = indexer.get(raw)
    if res is None:
        raise ValueError(f"tx ({_hex(raw)}) not found")
    if prove:
        # Merkle inclusion proof against the block's data hash (reference:
        # rpc/core/tx.go:47 + types/tx.go Txs.Proof; RFC 6962 tree).
        from tendermint_tpu.types.tx import txs_proof

        block = env.node.block_store.load_block(int(res["height"]))
        if block is None:
            # A proof cannot be constructed for a pruned block; degrading
            # to a proof-less result would read as "verified".
            raise ValueError(
                f"block at height {res['height']} not available for proof")
        idx = int(res["index"])
        txs = block.data.txs
        root, p = txs_proof(list(txs), idx)
        res = dict(res)
        res["proof"] = {
            "root_hash": _hex(root),
            "data": _b64(txs[idx]),
            "proof": {"total": str(p.total), "index": str(p.index),
                      "leaf_hash": _b64(p.leaf_hash),
                      "aunts": [_b64(a) for a in p.aunts]},
        }
    return res


def tx_search(env, query="", prove=False, page=1, per_page=30, order_by="asc"):
    indexer = getattr(env.node, "tx_indexer", None)
    if indexer is None:
        raise ValueError("transaction indexing is disabled")
    results = indexer.search(query)
    page, per_page = max(int(page), 1), min(max(int(per_page), 1), 100)
    start = (page - 1) * per_page
    return {"txs": results[start:start + per_page], "total_count": str(len(results))}


# --- abci routes ------------------------------------------------------------


def abci_query(env, path="", data="", height=0, prove=False):
    raw = bytes.fromhex(data) if isinstance(data, str) else data
    res = env.node.proxy_app.query.query(abci.RequestQuery(data=raw, path=path,
                                               height=int(height), prove=bool(prove)))
    return {"response": {
        "code": res.code, "log": res.log, "info": res.info,
        "index": str(res.index), "key": _b64(res.key), "value": _b64(res.value),
        "height": str(res.height), "codespace": res.codespace,
    }}


def abci_info(env):
    res = env.node.proxy_app.query.info(abci.RequestInfo())
    return {"response": {
        "data": res.data, "version": res.version,
        "app_version": str(res.app_version),
        "last_block_height": str(res.last_block_height),
        "last_block_app_hash": _b64(res.last_block_app_hash),
    }}


def light_block(env, height=0):
    """Hex-marshaled LightBlock for light clients / state sync.

    Not a reference route (the Go light provider assembles a LightBlock from
    /commit + paginated /validators, light/provider/http/http.go:65); one
    binary round-trip replaces 1+N/100 JSON ones. Error messages are part of
    the wire contract: HTTPProvider classifies 'must be less' as
    height-too-high and 'could not find' as not-found."""
    from tendermint_tpu.light.provider import (
        ErrHeightTooHigh,
        ErrLightBlockNotFound,
        NodeProvider,
    )

    h = int(height)
    # Byzantine-primary seam (consensus/misbehavior.py lunatic_proposer,
    # docs/BYZANTINE.md): a maverick node carries a map of fabricated
    # conflicting light blocks and serves THOSE to light clients instead
    # of its honest store — the staged light-client attack the detector +
    # evidence pipeline must catch. Production nodes never grow the
    # attribute, so this is dead code outside adversarial runs.
    fakes = getattr(env.node, "byzantine_light_blocks", None)
    if fakes:
        lb = fakes.get(h or env.node.block_store.height)
        if lb is not None:
            return {"height": str(lb.height), "light_block": lb.marshal().hex()}
    provider = NodeProvider(env.node.genesis.chain_id, env.node.block_store,
                            env.node.state_store)
    try:
        lb = provider.light_block(h)
    except ErrHeightTooHigh as e:
        raise ValueError(
            f"height {h} must be less than or equal to the current blockchain height"
        ) from e
    except ErrLightBlockNotFound as e:
        raise ValueError(f"could not find block: {e}") from e
    return {"height": str(lb.height), "light_block": lb.marshal().hex()}


def _light_gateway(env):
    """The node-local LightGateway (lazily built, cached on the node).

    The primary provider is the node's own self-healing stores; operators
    can cross-check against peer RPC endpoints via TMTPU_GATEWAY_PEERS
    (comma-separated base URLs become witness/spare HTTPProviders). Every
    gateway answer is light-client verified or refused — unlike the raw
    light_block route, which serves whatever the store (or a byzantine
    seam) holds."""
    gw = getattr(env.node, "_light_gateway", None)
    if gw is not None:
        return gw
    from tendermint_tpu.light.gateway import LightGateway, TrustOptions
    from tendermint_tpu.light.provider import HTTPProvider, NodeProvider
    from tendermint_tpu.light.store import DBStore
    from tendermint_tpu.store.db import MemDB

    chain_id = env.node.genesis.chain_id
    primary = NodeProvider(chain_id, env.node.block_store,
                           env.node.state_store)
    providers, names = [primary], ["local"]
    for url in os.environ.get("TMTPU_GATEWAY_PEERS", "").split(","):
        url = url.strip()
        if url:
            providers.append(HTTPProvider(chain_id, url))
            names.append(url)
    base = max(env.node.block_store.base, 1)
    anchor = primary.light_block(base)
    opts = TrustOptions(
        period_s=env.node.config.statesync.trust_period_s,
        height=anchor.height, hash=anchor.hash())
    gw = LightGateway(chain_id, opts, providers, DBStore(MemDB(), chain_id),
                      node=env.node, provider_names=names,
                      logger=getattr(env.node, "logger", None))
    env.node._light_gateway = gw
    return gw


def gateway_light_block(env, height=0):
    """Verified-or-refused light block through the node-local gateway
    (docs/LIGHT.md). height=0 serves the latest verified head."""
    from tendermint_tpu.light.gateway import ErrGatewayDegraded
    from tendermint_tpu.light.provider import (
        ErrHeightTooHigh,
        ErrLightBlockNotFound,
    )

    h = int(height)
    gw = _light_gateway(env)
    try:
        if h == 0:
            lb, verdict = gw.serve_latest()
        else:
            lb, verdict = gw.serve_light_block(h)
    except ErrHeightTooHigh as e:
        raise ValueError(
            f"height {h} must be less than or equal to the current blockchain height"
        ) from e
    except ErrLightBlockNotFound as e:
        raise ValueError(f"could not find block: {e}") from e
    except ErrGatewayDegraded as e:
        raise ValueError(str(e)) from e
    return {"height": str(lb.height), "light_block": lb.marshal().hex(),
            "verdict": verdict}


def gateway_tx(env, hash=""):
    """Tx + Merkle proof verified against a gateway-verified header; a
    quarantined store row refuses instead of serving corrupt bytes."""
    from tendermint_tpu.light.gateway import ErrGatewayDegraded
    from tendermint_tpu.light.provider import ErrLightBlockNotFound

    raw = base64.b64decode(hash) if isinstance(hash, str) else hash
    gw = _light_gateway(env)
    try:
        res = gw.serve_tx(raw)
    except ErrLightBlockNotFound as e:
        raise ValueError(str(e)) from e
    except ErrGatewayDegraded as e:
        raise ValueError(str(e)) from e
    p = res["proof"]
    return {
        "height": str(res["height"]),
        "index": str(res["index"]),
        "tx": _b64(res["tx"]),
        "verdict": res["verdict"],
        "proof": {
            "root_hash": _hex(res["root_hash"]),
            "proof": {"total": str(p.total), "index": str(p.index),
                      "leaf_hash": _b64(p.leaf_hash),
                      "aunts": [_b64(a) for a in p.aunts]},
        },
    }


def gateway_status(env):
    """Gateway introspection: provider scoreboard, cache, verdict counters."""
    return _light_gateway(env).describe()


def broadcast_evidence(env, evidence):
    """reference: rpc/core/evidence.go:17 BroadcastEvidence."""
    from tendermint_tpu.types.evidence import evidence_unmarshal

    ev = evidence_unmarshal(bytes.fromhex(evidence))
    env.node.evidence_pool.add_evidence(ev)
    return {"hash": ev.hash().hex()}


# --- unsafe control routes (reference: rpc/core/routes.go:51
# AddUnsafeRoutes, net.go UnsafeDialSeeds/UnsafeDialPeers,
# mempool.go UnsafeFlushMempool). The reference registers these only when
# config.RPC.Unsafe; here they are always routed but refuse unless
# rpc.unsafe is set — same reachable surface, clearer error. ------------


def _require_unsafe(env) -> None:
    cfg = getattr(getattr(env.node, "config", None), "rpc", None)
    if cfg is None or not cfg.unsafe:
        raise ValueError(
            "unsafe RPC routes are disabled (set rpc.unsafe = true)")


def _validated_addrs(addrs, what: str) -> list:
    """The reference parses every address up front and errors before any
    dialing (net.go UnsafeDialPeers -> NewNetAddressStrings)."""
    if not isinstance(addrs, list) or not addrs:
        raise ValueError(f"no {what} provided (expected a non-empty list)")
    for a in addrs:
        if (not isinstance(a, str) or "@" not in a
                or ":" not in a.rsplit("@", 1)[1]):
            raise ValueError(f"invalid {what[:-1]} address {a!r} "
                             "(expected id@host:port)")
    return addrs


def _dial_async(env, addrs: list, persistent: bool) -> None:
    """Dial in the background — a handler thread must not block for
    N x dial+handshake timeouts (reference dials via DialPeersAsync)."""
    import threading

    def run():
        for a in addrs:
            try:
                env.node.switch.dial_peer(a, persistent=persistent)
            except Exception:  # noqa: BLE001 - one refused dial must not
                # abandon the rest of the list
                continue

    threading.Thread(target=run, name="rpc-dial", daemon=True).start()


def dial_seeds(env, seeds=None):
    _require_unsafe(env)
    _dial_async(env, _validated_addrs(seeds, "seeds"), persistent=False)
    return {"log": "dialing seeds in progress; see /net_info"}


def dial_peers(env, peers=None, persistent=False, unconditional=False,
               private=False):
    _require_unsafe(env)
    if unconditional or private:
        # Reference semantics (net.go:41-66) mark peer ids unconditional/
        # private in the switch+PEX; this build has no such registry, and
        # silently ignoring the flags would mislead callers.
        raise ValueError("unconditional/private peer flags are not supported")
    _dial_async(env, _validated_addrs(peers, "peers"),
                persistent=bool(persistent))
    return {"log": "dialing peers in progress; see /net_info"}


def unsafe_flush_mempool(env):
    _require_unsafe(env)
    env.node.mempool.flush()
    return {}


def unsafe_peers(env, ban=None, unban=None, duration=None):
    """Peer misbehavior scoreboard view + manual ban control
    (utils/peerscore.py; no reference analogue — the overload-resilience
    plane's operator window, docs/OVERLOAD.md).

    With no params, returns scores, active bans (seconds remaining),
    per-offense counts, shed/rate-limit counters, and the threshold
    config. ``ban``/``unban``: a node id to sanction/pardon manually
    (``duration``: ban seconds, default = the configured schedule)."""
    _require_unsafe(env)
    board = _node_scoreboard(env)
    if board is None:
        raise ValueError("node has no peer scoreboard (switch not wired)")
    if ban is not None:
        if not isinstance(ban, str) or not ban:
            raise ValueError("ban must be a non-empty node id")
        board.ban(ban, float(duration) if duration is not None else None)
    if unban is not None:
        if not isinstance(unban, str) or not unban:
            raise ValueError("unban must be a non-empty node id")
        board.unban(unban)
    return board.describe()


def unsafe_nemesis(env, partition=None, heal=False, links=None):
    """Drive this node's peer-scoped link fault plane (utils/nemesis.py;
    no reference analogue — the e2e runner's partition/heal perturbations
    land here, the way runner/perturb.go drives docker network disconnects
    in the reference's containerized e2e).

    ``partition``: list of groups, each a list of node-id prefixes —
    installed symmetrically on every node of a testnet it cuts the links
    between groups. ``heal``: remove the partition (and re-kick persistent
    redials). ``links``: list of "src>dst:action[~p][%prob]" specs."""
    _require_unsafe(env)
    from tendermint_tpu.utils import nemesis

    if heal:
        nemesis.heal()
    if partition is not None:
        if (not isinstance(partition, list)
                or not all(isinstance(g, list) and g for g in partition)):
            raise ValueError("partition must be a list of non-empty groups")
        nemesis.partition(partition)
    if links is not None:
        if not isinstance(links, list):
            raise ValueError("links must be a list of src>dst:action specs")
        for spec in links:
            nemesis.add_link(spec)
    return nemesis.PLANE.describe()


def unsafe_scrub(env, repair=True, timeout=10.0):
    """On-demand storage-integrity scrub (store/scrub.py,
    docs/DURABILITY.md; no reference analogue — the self-healing storage
    plane's operator window).

    Walks the block/state/evidence/tx-index stores, verifies every
    record's CRC envelope + decode, quarantines anything rotten, and —
    with ``repair`` (default true) — synchronously drains the repair
    queue: blocks re-fetched from peers and batch-verified before rewrite,
    state rebuilt from the block store, index rows re-derived. With
    ``repair=false`` every finding is still SCHEDULED (quarantine deletes
    the live row, so dropping the repair would orphan it permanently) but
    drains on the repairer's background worker instead of blocking the
    call. Returns the damage map plus what was healed."""
    _require_unsafe(env)
    repairer = getattr(env.node, "store_repairer", None)
    do_repair = repair in (True, "true", "1", 1)
    report = env.node.scrubber().scrub(
        repairer=repairer, drain=do_repair,
        repair_timeout_s=float(timeout))
    out = report.as_dict()
    if repairer is not None:
        out["pending_repairs"] = [f"{k}:{a!r}" for k, a in repairer.pending()]
        out["needs_statesync"] = repairer.needs_statesync
    return out


def unsafe_trace(env, enable=None, clear=False, dump=False):
    """Flight-recorder control + summary view (utils/trace.py,
    docs/OBSERVABILITY.md; no reference analogue — the reference exposes
    pprof, this build's host-side recorder is span-structured).

    With no params: the tracer's state + per-span-name aggregation.
    ``enable``: true/false flips this node's tracer live. ``clear`` drops
    the ring. ``dump=true`` adds the raw span list (ring-bounded)."""
    _require_unsafe(env)
    tracer = getattr(env.node, "tracer", None)
    if tracer is None:
        raise ValueError("node has no tracer (utils/trace.py not wired)")
    if enable is not None:
        if enable in (True, "true", "1", 1):
            tracer.enable()
        elif enable in (False, "false", "0", 0):
            tracer.disable()
        else:
            raise ValueError("enable must be a boolean")
    if clear in (True, "true", "1", 1):
        tracer.clear()
    out = dict(tracer.describe())
    out["summary"] = tracer.summarize()
    if dump in (True, "true", "1", 1):
        out["spans"] = [s.as_dict() for s in tracer.dump()]
    return out


def unsafe_timeline(env, height=0):
    """Structured per-height block-lifecycle timeline from the node's
    flight recorder (docs/OBSERVABILITY.md schema): lifecycle marks,
    verify-pipeline phase durations, causal-order verdict. Default
    height: the latest committed block."""
    _require_unsafe(env)
    tracer = getattr(env.node, "tracer", None)
    if tracer is None:
        raise ValueError("node has no tracer (utils/trace.py not wired)")
    h = int(height) or env.node.block_store.height
    return tracer.timeline(h)


ROUTES = {
    "health": health,
    "status": status,
    "net_info": net_info,
    "genesis": genesis,
    "genesis_chunked": genesis_chunked,
    "blockchain": blockchain,
    "block": block,
    "block_by_hash": block_by_hash,
    "block_search": block_search,
    "header": header,
    "header_by_hash": header_by_hash,
    "block_results": block_results,
    "commit": commit,
    "light_block": light_block,
    "gateway_light_block": gateway_light_block,
    "gateway_tx": gateway_tx,
    "gateway_status": gateway_status,
    "validators": validators,
    "consensus_params": consensus_params,
    "consensus_state": consensus_state,
    "dump_consensus_state": dump_consensus_state,
    "unconfirmed_txs": unconfirmed_txs,
    "num_unconfirmed_txs": num_unconfirmed_txs,
    "broadcast_tx_async": broadcast_tx_async,
    "broadcast_tx_sync": broadcast_tx_sync,
    "broadcast_tx_commit": broadcast_tx_commit,
    "check_tx": check_tx,
    "tx": tx,
    "tx_search": tx_search,
    "abci_query": abci_query,
    "abci_info": abci_info,
    "broadcast_evidence": broadcast_evidence,
    # unsafe control routes: refuse unless rpc.unsafe (routes.go:51)
    "dial_seeds": dial_seeds,
    "dial_peers": dial_peers,
    "unsafe_flush_mempool": unsafe_flush_mempool,
    "unsafe_nemesis": unsafe_nemesis,
    "unsafe_peers": unsafe_peers,
    "unsafe_scrub": unsafe_scrub,
    "unsafe_trace": unsafe_trace,
    "unsafe_timeline": unsafe_timeline,
}
