"""JSON-RPC 2.0 API server: HTTP POST + URI GET + WebSocket subscriptions
(reference: rpc/jsonrpc/server/, rpc/core/routes.go:12-48).

Routes mirror the reference's ~35-route surface; handlers live in
tendermint_tpu.rpc.core and get the node injected (the reference's
rpccore.Environment pattern, node/node.go:1069).
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import socketserver
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from tendermint_tpu.rpc import core as rpc_core

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


def _rpc_response(id_, result=None, error: RPCError | None = None) -> bytes:
    doc = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        doc["error"] = {"code": error.code, "message": error.message, "data": error.data}
    else:
        doc["result"] = result
    return json.dumps(doc).encode()


class RPCServer:
    def __init__(self, node):
        self.node = node
        self.env = rpc_core.Environment(node)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self, laddr: str) -> None:
        host_port = laddr.split("://", 1)[-1]
        host, port = host_port.rsplit(":", 1)
        env = self.env

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, body: bytes, content_type="application/json", code=200):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.headers.get("Upgrade", "").lower() == "websocket":
                    self._websocket()
                    return
                url = urlparse(self.path)
                method = url.path.strip("/")
                if method == "":
                    self._send(_index_page(env), content_type="text/plain")
                    return
                params = {k: _parse_uri_param(v) for k, v in parse_qsl(url.query)}
                self._dispatch(method, params, id_=-1)

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    self._send(b"bad Content-Length", code=400)
                    return
                if length < 0:  # rfile.read(-1) would block to EOF
                    self._send(b"bad Content-Length", code=400)
                    return
                body = self.rfile.read(length)
                try:
                    req = json.loads(body)
                except ValueError:  # bad JSON or non-UTF-8 bytes
                    self._send(_rpc_response(0, error=RPCError(-32700, "Parse error")))
                    return
                if isinstance(req, list):
                    if not req:
                        # JSON-RPC 2.0 §6: an empty batch gets a single
                        # Invalid Request error object, not an array
                        self._send(_rpc_response(
                            0, error=RPCError(-32600, "Invalid Request")))
                        return
                    out = []
                    for r in req:
                        if not isinstance(r, dict):
                            out.append(json.loads(_rpc_response(
                                0, error=RPCError(-32600, "Invalid Request"))))
                            continue
                        out.append(json.loads(self._call(
                            r.get("method", ""), r.get("params", {}), r.get("id", 0))))
                    self._send(json.dumps(out).encode())
                    return
                if not isinstance(req, dict):
                    # null / scalar / string bodies are valid JSON but not
                    # JSON-RPC requests (spec §4: request must be an object)
                    self._send(_rpc_response(0, error=RPCError(-32600, "Invalid Request")))
                    return
                self._dispatch(req.get("method", ""), req.get("params", {}),
                               req.get("id", 0))

            def _dispatch(self, method, params, id_):
                self._send(self._call(method, params, id_))

            def _call(self, method, params, id_) -> bytes:
                # method/id straight from attacker JSON: method may be any
                # JSON value (an unhashable one would blow up dict.get)
                fn = (rpc_core.ROUTES.get(method)
                      if isinstance(method, str) else None)
                if fn is None:
                    return _rpc_response(id_, error=RPCError(
                        -32601, "Method not found", str(method)))
                try:
                    result = fn(env, **(params or {}))
                    return _rpc_response(id_, result=result)
                except TypeError as e:
                    return _rpc_response(id_, error=RPCError(-32602, "Invalid params", str(e)))
                except Exception as e:  # noqa: BLE001
                    return _rpc_response(id_, error=RPCError(-32603, "Internal error", str(e)))

            # --- WebSocket (RFC 6455 minimal server) -----------------------

            def _websocket(self):
                key = self.headers.get("Sec-WebSocket-Key", "")
                accept = base64.b64encode(
                    hashlib.sha1((key + WS_GUID).encode()).digest()
                ).decode()
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", accept)
                self.end_headers()
                conn = self.connection
                subscriber = f"ws-{id(conn)}"
                send_lock = threading.Lock()

                def ws_send(payload: bytes):
                    hdr = bytearray([0x81])
                    n = len(payload)
                    if n < 126:
                        hdr.append(n)
                    elif n < 65536:
                        hdr.append(126)
                        hdr += struct.pack(">H", n)
                    else:
                        hdr.append(127)
                        hdr += struct.pack(">Q", n)
                    with send_lock:
                        # per-socket write-serialization lock: frames from
                        # concurrent event pumps must not interleave
                        conn.sendall(bytes(hdr) + payload)  # tmlint: disable=lock-held-call

                subs: list = []
                try:
                    while True:
                        msg = _ws_read_frame(conn)
                        if msg is None:
                            break
                        try:
                            req = json.loads(msg)
                        except json.JSONDecodeError:
                            continue
                        if not isinstance(req, dict):
                            ws_send(_rpc_response(
                                0, error=RPCError(-32600, "Invalid Request")))
                            continue
                        method = req.get("method", "")
                        params = req.get("params", {})
                        id_ = req.get("id", 0)
                        if params is None:
                            params = {}
                        if not isinstance(params, dict):
                            # same verdict the HTTP path gives bad params;
                            # silently coercing would subscribe-to-all
                            ws_send(_rpc_response(id_, error=RPCError(
                                -32602, "Invalid params")))
                            continue
                        if method == "subscribe":
                            query = params.get("query", "")
                            sub = env.event_bus.subscribe(subscriber, query)
                            subs.append((sub, query, id_))
                            threading.Thread(
                                target=_pump_events,
                                args=(sub, ws_send, id_, query), daemon=True,
                            ).start()
                            ws_send(_rpc_response(id_, result={}))
                        elif method == "unsubscribe":
                            query = params.get("query", "")
                            env.event_bus.unsubscribe(subscriber, query)
                            ws_send(_rpc_response(id_, result={}))
                        elif method == "unsubscribe_all":
                            env.event_bus.unsubscribe_all(subscriber)
                            ws_send(_rpc_response(id_, result={}))
                        else:
                            fn = (rpc_core.ROUTES.get(method)
                                  if isinstance(method, str) else None)
                            if fn is None:
                                ws_send(_rpc_response(id_, error=RPCError(-32601, "Method not found")))
                            else:
                                try:
                                    ws_send(_rpc_response(id_, result=fn(env, **params)))
                                except Exception as e:  # noqa: BLE001
                                    ws_send(_rpc_response(id_, error=RPCError(-32603, "Internal error", str(e))))
                finally:
                    try:
                        env.event_bus.unsubscribe_all(subscriber)
                    except ValueError:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.laddr = f"tcp://{host}:{self._httpd.server_port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def _pump_events(sub, ws_send, id_, query):
    from tendermint_tpu.rpc import core as rpc_core

    while True:
        try:
            msg = sub.next(timeout=1.0)
        except Exception:  # noqa: BLE001
            return
        if msg is None:
            if sub.cancelled:
                return
            continue
        try:
            ws_send(_rpc_response(id_, result={
                "query": query,
                "data": rpc_core.encode_event_data(msg.data),
                "events": msg.events,
            }))
        except OSError:
            return


def _ws_read_frame(conn: socket.socket):
    hdr = _read_n(conn, 2)
    if hdr is None:
        return None
    b0, b1 = hdr
    opcode = b0 & 0x0F
    masked = b1 & 0x80
    ln = b1 & 0x7F
    if ln == 126:
        ext = _read_n(conn, 2)
        (ln,) = struct.unpack(">H", ext)
    elif ln == 127:
        ext = _read_n(conn, 8)
        (ln,) = struct.unpack(">Q", ext)
    mask = _read_n(conn, 4) if masked else b"\x00" * 4
    payload = _read_n(conn, ln) if ln else b""
    if payload is None:
        return None
    data = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    if opcode == 0x8:  # close
        return None
    if opcode == 0x9:  # ping -> pong
        conn.sendall(bytes([0x8A, len(data)]) + data)
        return b""
    return data


def _read_n(conn: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _parse_uri_param(v: str):
    if v.startswith('"') and v.endswith('"'):
        return v[1:-1]
    if v in ("true", "false"):
        return v == "true"
    try:
        return int(v)
    except ValueError:
        return v


def _index_page(env) -> bytes:
    lines = ["Available endpoints:"] + sorted(f"  /{m}" for m in rpc_core.ROUTES)
    return "\n".join(lines).encode()
