"""RPC client library (reference: rpc/client/{interface.go,http/http.go,
local/local.go}).

Two implementations of one surface:

 * ``HTTPClient`` — JSON-RPC 2.0 over HTTP POST, plus a WebSocket
   ``subscribe`` that yields events as they arrive (the reference http
   client's wsEvents, rpc/client/http/http.go:370).
 * ``LocalClient`` — direct in-process calls into the node's RPC
   environment, no sockets (rpc/client/local/local.go:23: "directly calls
   the methods the RPC server would"), with ``subscribe`` served straight
   off the EventBus.

Every method name matches the route it drives (rpc/core/routes.go:12-48),
and both clients raise ``RPCClientError`` on an error response, carrying
the server's code/message/data.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import socket
import struct
import threading
import urllib.request
from urllib.parse import urlparse

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class RPCClientError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"RPC error {code}: {message}"
                         + (f" ({data})" if data else ""))
        self.code = code
        self.message = message
        self.data = data


def _b64(raw: bytes) -> str:
    return base64.b64encode(raw).decode()


# Route name -> parameter names, generated onto both clients. Parameters are
# passed through as JSON-RPC params verbatim; defaults live server-side.
_METHODS = {
    "health": (),
    "status": (),
    "net_info": (),
    "genesis": (),
    "genesis_chunked": ("chunk",),
    "blockchain": ("minHeight", "maxHeight"),
    "block": ("height",),
    "block_by_hash": ("hash",),
    "block_search": ("query", "page", "per_page", "order_by"),
    "header": ("height",),
    "header_by_hash": ("hash",),
    "block_results": ("height",),
    "commit": ("height",),
    "light_block": ("height",),
    "validators": ("height", "page", "per_page"),
    "consensus_params": ("height",),
    "consensus_state": (),
    "dump_consensus_state": (),
    "unconfirmed_txs": ("limit",),
    "num_unconfirmed_txs": (),
    "tx_search": ("query", "prove", "page", "per_page", "order_by"),
    "abci_info": (),
}


class _ClientBase:
    """Shared method generation; subclasses provide _call(method, params)."""

    def __getattr__(self, name):
        sig = _METHODS.get(name)
        if sig is None:
            raise AttributeError(name)

        def method(*args, **kw):
            if len(args) > len(sig):
                raise TypeError(f"{name} takes at most {len(sig)} arguments")
            params = dict(zip(sig, args))
            params.update(kw)
            return self._call(name, {k: v for k, v in params.items()
                                     if v is not None})

        method.__name__ = name
        return method

    # -- byte-argument helpers (reference http client marshals these,
    # rpc/client/http/http.go:280-350) ---------------------------------------

    def broadcast_tx_sync(self, tx: bytes):
        return self._call("broadcast_tx_sync", {"tx": _b64(tx)})

    def broadcast_tx_async(self, tx: bytes):
        return self._call("broadcast_tx_async", {"tx": _b64(tx)})

    def broadcast_tx_commit(self, tx: bytes):
        return self._call("broadcast_tx_commit", {"tx": _b64(tx)})

    def check_tx(self, tx: bytes):
        return self._call("check_tx", {"tx": _b64(tx)})

    def tx(self, hash: bytes, prove: bool = False):
        return self._call("tx", {"hash": _b64(hash), "prove": prove})

    def abci_query(self, path: str, data: bytes, height: int = 0,
                   prove: bool = False):
        return self._call("abci_query", {
            "path": path, "data": data.hex(), "height": height,
            "prove": prove})

    def broadcast_evidence(self, ev_hex: str):
        return self._call("broadcast_evidence", {"evidence": ev_hex})


class HTTPClient(_ClientBase):
    """reference: rpc/client/http/http.go:28 HTTP."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        if base_url.startswith("tcp://"):
            base_url = "http://" + base_url[len("tcp://"):]
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._id = 0
        self._id_mtx = threading.Lock()

    def remote(self) -> str:
        return self.base_url

    def _next_id(self) -> int:
        with self._id_mtx:
            self._id += 1
            return self._id

    def _call(self, method: str, params: dict):
        body = json.dumps({"jsonrpc": "2.0", "id": self._next_id(),
                           "method": method, "params": params}).encode()
        req = urllib.request.Request(
            self.base_url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            doc = json.loads(r.read())
        if "error" in doc:
            e = doc["error"]
            raise RPCClientError(e.get("code", -1), e.get("message", ""),
                                 e.get("data", ""))
        return doc["result"]

    def subscribe(self, query: str, timeout: float | None = None):
        """Yield event payloads matching ``query`` over a dedicated
        WebSocket. Each yield is the subscription result dict
        ({"query", "data", "events"}). Generator close() tears the socket
        down. ``timeout`` bounds the wait for EACH event."""
        u = urlparse(self.base_url)
        host, port = u.hostname, u.port or 80
        conn = socket.create_connection((host, port),
                                        timeout=timeout or self.timeout)
        try:
            key = base64.b64encode(os.urandom(16)).decode()
            conn.sendall((
                f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
                f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
            resp = b""
            while b"\r\n\r\n" not in resp:
                chunk = conn.recv(4096)
                if not chunk:
                    raise ConnectionError("websocket handshake failed")
                resp += chunk
            if b"101" not in resp.split(b"\r\n", 1)[0]:
                raise ConnectionError("websocket upgrade refused")
            expect = base64.b64encode(hashlib.sha1(
                (key + WS_GUID).encode()).digest())
            if expect not in resp:
                raise ConnectionError("bad Sec-WebSocket-Accept")
            _ws_send(conn, json.dumps({
                "jsonrpc": "2.0", "id": 1, "method": "subscribe",
                "params": {"query": query}}).encode())
            # First frame is the subscribe ack.
            ack = json.loads(_ws_recv(conn))
            if "error" in ack:
                e = ack["error"]
                raise RPCClientError(e.get("code", -1), e.get("message", ""),
                                     e.get("data", ""))
            while True:
                doc = json.loads(_ws_recv(conn))
                if "error" in doc:
                    e = doc["error"]
                    raise RPCClientError(e.get("code", -1),
                                         e.get("message", ""),
                                         e.get("data", ""))
                result = doc.get("result") or {}
                if result:
                    yield result
        finally:
            conn.close()


def _ws_send(conn: socket.socket, payload: bytes) -> None:
    """One masked text frame (clients MUST mask, RFC 6455 §5.3)."""
    mask = os.urandom(4)
    hdr = bytearray([0x81])
    n = len(payload)
    if n < 126:
        hdr.append(0x80 | n)
    elif n < 65536:
        hdr.append(0x80 | 126)
        hdr += struct.pack(">H", n)
    else:
        hdr.append(0x80 | 127)
        hdr += struct.pack(">Q", n)
    hdr += mask
    conn.sendall(bytes(hdr)
                 + bytes(b ^ mask[i % 4] for i, b in enumerate(payload)))


def _ws_recv(conn: socket.socket) -> bytes:
    while True:
        hdr = _read_n(conn, 2)
        b0, b1 = hdr
        opcode = b0 & 0x0F
        ln = b1 & 0x7F
        if ln == 126:
            (ln,) = struct.unpack(">H", _read_n(conn, 2))
        elif ln == 127:
            (ln,) = struct.unpack(">Q", _read_n(conn, 8))
        mask = _read_n(conn, 4) if b1 & 0x80 else None
        payload = _read_n(conn, ln) if ln else b""
        if mask:
            payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        if opcode == 0x8:
            raise ConnectionError("websocket closed by server")
        if opcode == 0x9:  # ping -> pong
            conn.sendall(bytes([0x8A, len(payload)]) + payload)
            continue
        if payload == b"":  # server's pong or empty frame
            continue
        return payload


def _read_n(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("websocket closed mid-frame")
        buf += chunk
    return buf


class LocalClient(_ClientBase):
    """reference: rpc/client/local/local.go:40 New."""

    SUBSCRIBER = "rpc-local-client"

    def __init__(self, node):
        from tendermint_tpu.rpc import core as rpc_core

        self._env = rpc_core.Environment(node)
        self._routes = rpc_core.ROUTES
        self._node = node
        self._sub_seq = 0
        self._sub_mtx = threading.Lock()

    def remote(self) -> str:
        return "local"

    def _call(self, method: str, params: dict):
        fn = self._routes.get(method)
        if fn is None:
            raise RPCClientError(-32601, "Method not found", method)
        try:
            return fn(self._env, **params)
        except RPCClientError:
            raise
        except Exception as e:  # noqa: BLE001 - mirror the server boundary
            raise RPCClientError(-32603, "Internal error", str(e)) from e

    def subscribe(self, query: str, timeout: float | None = None):
        from tendermint_tpu.rpc import core as rpc_core

        with self._sub_mtx:
            self._sub_seq += 1
            subscriber = f"{self.SUBSCRIBER}-{self._sub_seq}"
        from tendermint_tpu.types.events import SubscriptionCancelled

        sub = self._node.event_bus.subscribe(subscriber, query)
        try:
            while True:
                try:
                    msg = sub.next(timeout=timeout or 1.0)
                except SubscriptionCancelled:
                    # bus shutdown with an empty queue: clean end of
                    # iteration, not an internal exception escaping the
                    # generator (round-4 advisor finding)
                    return
                if msg is None:
                    if sub.cancelled:
                        return
                    continue
                yield {"query": query,
                       "data": rpc_core.encode_event_data(msg.data),
                       "events": msg.events}
        finally:
            try:
                self._node.event_bus.unsubscribe_all(subscriber)
            except ValueError:
                pass
