"""State sync (reference: statesync/).

Bootstrap a fresh node from an ABCI application snapshot instead of
replaying every block: discover snapshots from peers (channel 0x60), offer
the best one to the local app, fetch + apply its chunks (channel 0x61),
then verify the restored app hash against a light-client-verified header
and install the fetched State/Commit so fast sync can take over at
height+1.

 - snapshots: Snapshot + peer-tracking pool with ranking
 - chunks: chunk queue for the snapshot being restored
 - syncer: the offer/fetch/apply/verify state machine
 - stateprovider: light-client-backed State/Commit/AppHash source
 - reactor: p2p wiring (serving + syncing sides)
"""

from tendermint_tpu.statesync.reactor import (
    CHUNK_CHANNEL,
    SNAPSHOT_CHANNEL,
    StateSyncReactor,
)
from tendermint_tpu.statesync.snapshots import Snapshot, SnapshotPool
from tendermint_tpu.statesync.stateprovider import LightClientStateProvider
from tendermint_tpu.statesync.syncer import SyncError, Syncer

__all__ = [
    "StateSyncReactor",
    "SNAPSHOT_CHANNEL",
    "CHUNK_CHANNEL",
    "Snapshot",
    "SnapshotPool",
    "LightClientStateProvider",
    "Syncer",
    "SyncError",
]
