"""Light-client-backed state provider (reference:
statesync/stateprovider.go:48 NewLightClientStateProvider).

The trust anchor for state sync: every app hash / commit / State handed to
the syncer is backed by light-client-verified headers, so a lying snapshot
peer can at worst waste bandwidth, never forge state.

Verification cost: each verify_light_block_at_height runs the light
client's verify_commit_light(_trusting) through the BatchVerifier registry,
which routes kernel-worthy flushes onto the continuous-batching verify
service (crypto/verify_service.py) — a statesync bootstrap racing the
node's other verify traffic (consensus drain, fast-sync) shares kernel
launches and sync floors with it instead of paying its own.
"""

from __future__ import annotations

import threading

from tendermint_tpu.light.client import Client, TrustOptions
from tendermint_tpu.light.store import DBStore
from tendermint_tpu.state.state import State
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.types.block import BLOCK_PROTOCOL, Consensus
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.ttime import Time


class StateProviderError(Exception):
    pass


class LightClientStateProvider:
    """reference: statesync/stateprovider.go:27-40 (interface) + :48."""

    def __init__(self, chain_id: str, version_app: int,
                 trust_options: TrustOptions, primary, witnesses,
                 consensus_params: ConsensusParams | None = None,
                 initial_height: int = 1, logger=None):
        self.chain_id = chain_id
        self.version_app = version_app
        self.initial_height = initial_height
        # The reference fetches consensus params over RPC from a witness
        # (stateprovider.go:142); here they're supplied from the genesis doc
        # the operator already has (params changes mid-chain would need the
        # RPC fetch -- documented gap, params updates via ABCI are rare).
        self.consensus_params = consensus_params or ConsensusParams()
        self._mtx = threading.Lock()
        self._client = Client(
            chain_id, trust_options, primary, list(witnesses),
            DBStore(MemDB(), prefix="ssp"), logger=logger,
            max_clock_drift_s=120.0,
        )

    def _light_block(self, height: int):
        return self._client.verify_light_block_at_height(height, Time.now())

    def app_hash(self, height: int) -> bytes:
        """App hash AFTER applying block `height` lives in header height+1
        (reference: stateprovider.go:78 AppHash)."""
        with self._mtx:
            return self._light_block(height + 1).signed_header.header.app_hash

    def commit(self, height: int):
        """reference: stateprovider.go:92."""
        with self._mtx:
            return self._light_block(height).signed_header.commit

    def state(self, height: int) -> State:
        """Reconstruct the post-block-`height` State from verified headers
        (reference: stateprovider.go:100-140)."""
        with self._mtx:
            # State convention (state/state.py:29): validators apply at
            # last_block_height+1, next_validators at +2, last_validators at
            # the committed height itself.  Light block at X carries
            # valset(X), so fetch H, H+1, H+2 (reference:
            # statesync/stateprovider.go:146-170).
            last = self._light_block(height)
            cur = self._light_block(height + 1)
            nxt = self._light_block(height + 2)
            return State(
                version=Consensus(block=BLOCK_PROTOCOL, app=self.version_app),
                chain_id=self.chain_id,
                initial_height=self.initial_height,
                last_block_height=last.height,
                last_block_id=cur.signed_header.header.last_block_id,
                last_block_time=last.signed_header.header.time,
                validators=cur.validator_set,
                next_validators=nxt.validator_set,
                last_validators=last.validator_set,
                last_height_validators_changed=nxt.height,
                consensus_params=self.consensus_params,
                last_height_consensus_params_changed=self.initial_height,
                last_results_hash=cur.signed_header.header.last_results_hash,
                app_hash=cur.signed_header.header.app_hash,
            )
