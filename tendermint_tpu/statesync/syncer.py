"""The state-sync state machine (reference: statesync/syncer.go).

Drives one restore attempt end to end: pick the best discovered snapshot,
offer it to the local ABCI app, fetch + apply chunks in order, then verify
the restored app hash against the light-client state provider.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.abci import types as abci
from tendermint_tpu.statesync.chunks import ChunkQueue
from tendermint_tpu.statesync.snapshots import Snapshot, SnapshotPool


class SyncError(Exception):
    pass


class ErrNoSnapshots(SyncError):
    """reference: statesync/syncer.go:31 errNoSnapshots."""


class ErrAbort(SyncError):
    """App aborted the snapshot restore (reference: syncer.go:27 errAbort)."""


class ErrRejectSnapshot(SyncError):
    pass


class ErrRejectFormat(SyncError):
    pass


class ErrVerifyFailed(SyncError):
    """Restored app hash does not match the trusted header (reference:
    syncer.go:35 errVerifyFailed)."""


class Syncer:
    """reference: statesync/syncer.go:49 syncer."""

    def __init__(self, app, state_provider, *, chunk_request_timeout_s: float = 10.0,
                 chunk_fetchers: int = 4, logger=None):
        self.app = app  # ABCI snapshot connection (Application)
        self.state_provider = state_provider
        self.pool = SnapshotPool()
        self.chunk_request_timeout_s = chunk_request_timeout_s
        self.chunk_fetchers = chunk_fetchers
        self.logger = logger
        self._chunks: ChunkQueue | None = None
        self._mtx = threading.Lock()
        # set by the reactor: fn(peer_id, height, format, index) requesting a
        # chunk from a peer over channel 0x61
        self.request_chunk = lambda peer_id, height, fmt, index: None
        # set by the reactor: fn() re-broadcasting SnapshotsRequest to every
        # current peer. Discovery would otherwise be ONE-SHOT (a request at
        # add_peer time): a syncer that exhausts its known snapshots — e.g.
        # the first attempt raced the trust chain and got rejected — would
        # wait out the whole give-up window while its peers keep taking
        # NEWER snapshots it never hears about (found by the fabric's
        # in-process churn scenario, tests/test_fabric.py).
        self.request_snapshots = lambda: None
        # peer misbehavior scoreboard (utils/peerscore.py), set by node
        # wiring: an app-level reject_senders verdict is the strongest
        # attribution statesync has — it scores, not just pool-rejects
        self.scoreboard = None

    # --- discovery input ----------------------------------------------------

    def add_snapshot(self, peer_id: str, snapshot: Snapshot) -> bool:
        return self.pool.add(peer_id, snapshot)

    def add_chunk(self, index: int, chunk: bytes, sender: str) -> bool:
        with self._mtx:
            q = self._chunks
        return q.add(index, chunk, sender) if q is not None else False

    def remove_peer(self, peer_id: str) -> None:
        self.pool.remove_peer(peer_id)

    # --- the sync loop (reference: syncer.go:145 SyncAny) -------------------

    def sync_any(self, discovery_time_s: float, give_up_after_s: float = 120.0):
        """Try snapshots best-first until one restores and verifies.
        Returns (state, commit)."""
        deadline = time.monotonic() + give_up_after_s
        tried: set[bytes] = set()
        transient_retries: dict[bytes, int] = {}
        next_discovery = 0.0
        while time.monotonic() < deadline:
            snapshot = None
            for s in self.pool.ranked():
                if s.key() not in tried:
                    snapshot = s
                    break
            if snapshot is None:
                # out of candidates: re-poll the peers (paced by the
                # discovery interval) — they advertise newer snapshots as
                # their apps take them, and a snapshot that failed for a
                # transient reason gets a second look once rediscovered
                now = time.monotonic()
                if now >= next_discovery:
                    next_discovery = now + max(discovery_time_s, 0.1)
                    tried.clear()
                    self.request_snapshots()
                time.sleep(min(discovery_time_s, 0.1))
                continue
            tried.add(snapshot.key())
            try:
                return self.sync(snapshot)
            except ErrRejectSnapshot:
                self.pool.reject(snapshot)
            except ErrRejectFormat:
                self.pool.reject_format(snapshot.format)
            except ErrVerifyFailed:
                # Snapshot content didn't match the trusted app hash: ban the
                # peers that advertised it (reference: syncer.go:168-178).
                for pid in self.pool.peers_of(snapshot):
                    self.pool.reject_peer(pid)
                self.pool.reject(snapshot)
            except ErrAbort:
                raise
            except SyncError:
                self.pool.reject(snapshot)
            except Exception as e:  # noqa: BLE001
                # Transient provider/light-client failure -- typically the
                # trust chain can't serve app_hash(H) yet because header H+1
                # hasn't landed on the RPC node. Retry the SAME snapshot a
                # few times, then reject it so lower-ranked snapshots get a
                # turn (a deterministic failure must not starve them).
                n = transient_retries.get(snapshot.key(), 0) + 1
                transient_retries[snapshot.key()] = n
                if self.logger:
                    self.logger.info("state sync attempt failed",
                                     err=e, attempt=n)
                if n < 6:
                    tried.discard(snapshot.key())
                    time.sleep(0.5)
                else:
                    self.pool.reject(snapshot)
        raise ErrNoSnapshots("no viable snapshot found before deadline")

    def sync(self, snapshot: Snapshot):
        """Restore one snapshot (reference: syncer.go:241 Sync)."""
        if self.logger:
            self.logger.info("state sync: attempting snapshot",
                             height=snapshot.height, format=snapshot.format,
                             chunks=snapshot.chunks)
        # 1. Trusted app hash for this height MUST exist before offering
        #    (reference: syncer.go:259 -- never feed the app unverified data).
        app_hash = self.state_provider.app_hash(snapshot.height)

        # 2. Offer to the app.
        self._offer_snapshot(snapshot, app_hash)
        if self.logger:
            self.logger.info("state sync: snapshot accepted, fetching chunks",
                             height=snapshot.height)

        # 3. Fetch + apply chunks.
        with self._mtx:
            self._chunks = ChunkQueue(snapshot.chunks)
        try:
            fetchers = [
                threading.Thread(target=self._fetch_routine, args=(snapshot,),
                                 daemon=True)
                for _ in range(min(self.chunk_fetchers, max(snapshot.chunks, 1)))
            ]
            for f in fetchers:
                f.start()
            self._apply_chunks(snapshot)
        finally:
            with self._mtx:
                q, self._chunks = self._chunks, None
            if q is not None:
                q.close()

        # 4. Verify the restored app against the trusted header
        #    (reference: syncer.go:432 verifyApp).
        info = self.app.info(abci.RequestInfo())
        if info.last_block_app_hash != app_hash:
            raise ErrVerifyFailed(
                f"app hash mismatch after restore: expected {app_hash.hex()}, "
                f"got {info.last_block_app_hash.hex()}")
        if info.last_block_height != snapshot.height:
            raise ErrVerifyFailed(
                f"app height mismatch: expected {snapshot.height}, "
                f"got {info.last_block_height}")

        # 5. Fetch the State + Commit the node resumes from.
        state = self.state_provider.state(snapshot.height)
        commit = self.state_provider.commit(snapshot.height)
        return state, commit

    # --- internals ----------------------------------------------------------

    def _offer_snapshot(self, snapshot: Snapshot, app_hash: bytes) -> None:
        """reference: syncer.go:322 offerSnapshot."""
        resp = self.app.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=abci.Snapshot(
                height=snapshot.height, format=snapshot.format,
                chunks=snapshot.chunks, hash=snapshot.hash,
                metadata=snapshot.metadata),
            app_hash=app_hash,
        ))
        r = resp.result
        if r == abci.OFFER_SNAPSHOT_ACCEPT:
            return
        if r == abci.OFFER_SNAPSHOT_ABORT:
            raise ErrAbort("app aborted state sync")
        if r == abci.OFFER_SNAPSHOT_REJECT:
            raise ErrRejectSnapshot("app rejected snapshot")
        if r == abci.OFFER_SNAPSHOT_REJECT_FORMAT:
            raise ErrRejectFormat(f"app rejected format {snapshot.format}")
        if r == abci.OFFER_SNAPSHOT_REJECT_SENDER:
            raise ErrRejectSnapshot("app rejected snapshot senders")
        raise SyncError(f"unknown OfferSnapshot result {r}")

    def _fetch_routine(self, snapshot: Snapshot) -> None:
        """Request unfetched chunks from peers that have this snapshot
        (reference: syncer.go:380 fetchChunks)."""
        while True:
            try:
                with self._mtx:
                    q = self._chunks
                if q is None or q.done():
                    return
                idx = q.allocate(time.monotonic(), self.chunk_request_timeout_s)
                if idx is None:
                    time.sleep(0.05)
                    continue
                peers = self.pool.peers_of(snapshot)
                if not peers:
                    time.sleep(0.1)
                    continue
                peer = peers[idx % len(peers)]
                self.request_chunk(peer, snapshot.height, snapshot.format, idx)
                time.sleep(0.01)
            except Exception as e:  # noqa: BLE001 - a transient p2p blip
                # must not kill the fetcher (apply would then time out);
                # retry-allocation handles any chunk left in flight
                if self.logger:
                    self.logger.error("chunk fetch iteration failed", err=e)
                time.sleep(0.1)

    def _apply_chunks(self, snapshot: Snapshot) -> None:
        """Apply in strict order, honoring refetch/ban feedback (reference:
        syncer.go:358 applyChunks)."""
        with self._mtx:
            q = self._chunks
        while not q.done():
            nxt = q.next(self.chunk_request_timeout_s * 2)
            if nxt is None:
                raise SyncError("timed out waiting for chunk")
            index, body, sender = nxt
            resp = self.app.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(
                index=index, chunk=body, sender=sender))
            for s in resp.reject_senders:
                self.pool.reject_peer(s)
                if self.scoreboard is not None and s:
                    self.scoreboard.record(s, "statesync_reject")
                for freed in q.discard_sender(s):
                    q.retry(freed)
            for r in resp.refetch_chunks:
                q.retry(r)
            if resp.result == abci.APPLY_CHUNK_ACCEPT:
                continue
            if resp.result == abci.APPLY_CHUNK_RETRY:
                q.retry(index)
                continue
            if resp.result == abci.APPLY_CHUNK_RETRY_SNAPSHOT:
                raise ErrRejectSnapshot("app requested snapshot retry")
            if resp.result == abci.APPLY_CHUNK_ABORT:
                raise ErrAbort("app aborted during chunk apply")
            if resp.result == abci.APPLY_CHUNK_REJECT_SNAPSHOT:
                raise ErrRejectSnapshot("app rejected snapshot during apply")
            raise SyncError(f"unknown ApplySnapshotChunk result {resp.result}")
