"""Chunk queue for the snapshot being restored (reference:
statesync/chunks.go).

The reference spills chunks to a temp dir; chunks here stay in memory —
snapshot chunks are bounded (the syncer fetches a window, applies in order,
and discards), so the OS page cache indirection buys nothing on a TPU host
with hundreds of GB of RAM.
"""

from __future__ import annotations

import threading


class ChunkQueue:
    """reference: statesync/chunks.go:27 chunkQueue."""

    def __init__(self, num_chunks: int):
        self.num_chunks = num_chunks
        self._chunks: dict[int, tuple[bytes, str]] = {}  # index -> (body, sender)
        self._allocated: dict[int, float] = {}  # index -> request time
        self._returned: set[int] = set()  # applied indexes
        self._cv = threading.Condition()
        self._closed = False

    def add(self, index: int, chunk: bytes, sender: str) -> bool:
        """Store a fetched chunk; returns False for dupes/out-of-range
        (reference: chunks.go:86 Add)."""
        with self._cv:
            if self._closed or not (0 <= index < self.num_chunks):
                return False
            if index in self._chunks or index in self._returned:
                return False
            self._chunks[index] = (chunk, sender)
            self._allocated.pop(index, None)
            self._cv.notify_all()
            return True

    def allocate(self, now: float, timeout: float) -> int | None:
        """Next index worth requesting: unfetched and not recently requested
        (reference: chunks.go:158 Allocate)."""
        with self._cv:
            for i in range(self.num_chunks):
                if i in self._chunks or i in self._returned:
                    continue
                at = self._allocated.get(i)
                if at is not None and now - at < timeout:
                    continue
                self._allocated[i] = now
                return i
            return None

    def next(self, timeout: float) -> tuple[int, bytes, str] | None:
        """Block until the NEXT in-order chunk is available (reference:
        chunks.go:230 Next -- apply order is strict). The next wanted index
        is the smallest unapplied one (retry() can reopen earlier indexes)."""
        with self._cv:
            while not self._closed:
                want = min(
                    (i for i in range(self.num_chunks) if i not in self._returned),
                    default=None,
                )
                if want is None:
                    return None
                if want in self._chunks:
                    body, sender = self._chunks.pop(want)
                    self._returned.add(want)
                    return want, body, sender
                if not self._cv.wait(timeout):
                    return None
            return None

    def retry(self, index: int) -> None:
        """Re-queue an applied-but-rejected chunk (reference: chunks.go:260
        Retry)."""
        with self._cv:
            self._returned.discard(index)
            self._allocated.pop(index, None)

    def discard_sender(self, sender: str) -> list[int]:
        """Drop all unapplied chunks from a banned sender; returns the
        indexes freed (reference: chunks.go:120 DiscardSender)."""
        with self._cv:
            freed = [i for i, (_, s) in self._chunks.items() if s == sender]
            for i in freed:
                del self._chunks[i]
                self._allocated.pop(i, None)
            return freed

    def done(self) -> bool:
        with self._cv:
            return len(self._returned) >= self.num_chunks

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
