"""State-sync p2p reactor (reference: statesync/reactor.go; channels
Snapshot=0x60, Chunk=0x61; proto/tendermint/statesync/types.proto).

Serving side answers SnapshotsRequest/ChunkRequest from the local app;
syncing side feeds discovered snapshots + fetched chunks into the Syncer
and drives one bootstrap attempt via `sync()`.

Messages (oneof field numbers from the reference proto):
  SnapshotsRequest=1{}, SnapshotsResponse=2{height,format,chunks,hash,metadata},
  ChunkRequest=3{height,format,index}, ChunkResponse=4{height,format,index,chunk,missing}.
"""

from __future__ import annotations

from tendermint_tpu.abci import types as abci
from tendermint_tpu.encoding import proto
from tendermint_tpu.p2p.connection import ChannelDescriptor
from tendermint_tpu.p2p.switch import Peer, Reactor
from tendermint_tpu.statesync.snapshots import RECENT_SNAPSHOTS, Snapshot
from tendermint_tpu.statesync.syncer import Syncer

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61


def msg_snapshots_request() -> bytes:
    return proto.Writer().message(1, b"", always=True).out()


def msg_snapshots_response(s: Snapshot) -> bytes:
    inner = (proto.Writer().varint(1, s.height).varint(2, s.format)
             .varint(3, s.chunks).bytes(4, s.hash).bytes(5, s.metadata).out())
    return proto.Writer().message(2, inner, always=True).out()


def msg_chunk_request(height: int, fmt: int, index: int) -> bytes:
    inner = proto.Writer().varint(1, height).varint(2, fmt).varint(3, index).out()
    return proto.Writer().message(3, inner, always=True).out()


def msg_chunk_response(height: int, fmt: int, index: int, chunk: bytes,
                       missing: bool) -> bytes:
    w = proto.Writer().varint(1, height).varint(2, fmt).varint(3, index)
    w.bytes(4, chunk)
    if missing:
        w.varint(5, 1)
    return proto.Writer().message(4, w.out(), always=True).out()


class StateSyncReactor(Reactor):
    """reference: statesync/reactor.go:36."""

    def __init__(self, app, syncer: Syncer | None = None, logger=None):
        super().__init__("STATESYNC")
        self.app = app  # local ABCI app, serving side
        self.syncer = syncer  # set when this node wants to sync
        self.logger = logger
        if syncer is not None:
            syncer.request_chunk = self._request_chunk
            syncer.request_snapshots = self._request_snapshots

    def get_channels(self) -> list[ChannelDescriptor]:
        # priorities/capacities from reference reactor.go:58-77
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5,
                              recv_message_capacity=4 * 1024 * 1024),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3,
                              recv_message_capacity=16 * 1024 * 1024),
        ]

    def add_peer(self, peer: Peer) -> None:
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, msg_snapshots_request())

    def remove_peer(self, peer: Peer, reason) -> None:
        if self.syncer is not None:
            self.syncer.remove_peer(peer.id)

    # --- receive ------------------------------------------------------------

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        f = proto.fields(msg_bytes)
        if ch_id == SNAPSHOT_CHANNEL:
            if 1 in f:  # SnapshotsRequest
                self._serve_snapshots(peer)
            elif 2 in f:  # SnapshotsResponse
                m = proto.fields(f[2][-1])
                s = Snapshot(
                    height=proto.as_sint64(m.get(1, [0])[-1]),
                    format=proto.as_sint64(m.get(2, [0])[-1]),
                    chunks=proto.as_sint64(m.get(3, [0])[-1]),
                    hash=m.get(4, [b""])[-1],
                    metadata=m.get(5, [b""])[-1],
                )
                if self.syncer is not None:
                    self.syncer.add_snapshot(peer.id, s)
        elif ch_id == CHUNK_CHANNEL:
            if 3 in f:  # ChunkRequest
                m = proto.fields(f[3][-1])
                self._serve_chunk(
                    peer,
                    proto.as_sint64(m.get(1, [0])[-1]),
                    proto.as_sint64(m.get(2, [0])[-1]),
                    proto.as_sint64(m.get(3, [0])[-1]),
                )
            elif 4 in f:  # ChunkResponse
                m = proto.fields(f[4][-1])
                index = proto.as_sint64(m.get(3, [0])[-1])
                chunk = m.get(4, [b""])[-1]
                missing = bool(proto.as_sint64(m.get(5, [0])[-1]))
                if self.syncer is not None and not missing:
                    self.syncer.add_chunk(index, chunk, peer.id)

    # --- serving side (reference: reactor.go:106-170) -----------------------

    def _serve_snapshots(self, peer: Peer) -> None:
        try:
            resp = self.app.list_snapshots(abci.RequestListSnapshots())
        except Exception:  # noqa: BLE001 - peer input must not kill the reactor
            return
        for s in resp.snapshots[:RECENT_SNAPSHOTS]:
            peer.try_send(SNAPSHOT_CHANNEL, msg_snapshots_response(Snapshot(
                height=s.height, format=s.format, chunks=s.chunks,
                hash=s.hash, metadata=s.metadata)))

    def _serve_chunk(self, peer: Peer, height: int, fmt: int, index: int) -> None:
        try:
            resp = self.app.load_snapshot_chunk(abci.RequestLoadSnapshotChunk(
                height=height, format=fmt, chunk=index))
        except Exception:  # noqa: BLE001
            resp = None
        chunk = resp.chunk if resp is not None else b""
        peer.try_send(CHUNK_CHANNEL, msg_chunk_response(
            height, fmt, index, chunk, missing=not chunk))

    # --- syncing side -------------------------------------------------------

    def _request_chunk(self, peer_id: str, height: int, fmt: int, index: int) -> None:
        if self.switch is None:
            return
        with self.switch._peers_mtx:
            p = self.switch.peers.get(peer_id)
        if p is not None:
            p.try_send(CHUNK_CHANNEL, msg_chunk_request(height, fmt, index))

    def _request_snapshots(self) -> None:
        """Re-broadcast SnapshotsRequest to every current peer: the syncer
        calls this when its candidate pool runs dry, so serving nodes'
        NEWER snapshots (taken after our add_peer hello) become visible."""
        if self.switch is None:
            return
        self.switch.broadcast(SNAPSHOT_CHANNEL, msg_snapshots_request())

    def sync(self, discovery_time_s: float, give_up_after_s: float = 120.0):
        """Run one bootstrap attempt; returns (state, commit) (reference:
        reactor.go:282 Sync)."""
        if self.syncer is None:
            raise RuntimeError("reactor has no syncer configured")
        if self.switch is not None:
            self.switch.broadcast(SNAPSHOT_CHANNEL, msg_snapshots_request())
        return self.syncer.sync_any(discovery_time_s, give_up_after_s)
