"""Snapshot metadata + discovery pool (reference: statesync/snapshots.go)."""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field

# Limit on snapshots advertised per peer (reference:
# statesync/snapshots.go:16 recentSnapshots=10).
RECENT_SNAPSHOTS = 10


@dataclass(frozen=True)
class Snapshot:
    """reference: statesync/snapshots.go:20-36."""

    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""

    def key(self) -> bytes:
        """Distinct snapshots may share height/format; identity includes the
        content hash (reference: snapshots.go:29 Key)."""
        h = hashlib.sha256()
        h.update(self.height.to_bytes(8, "big"))
        h.update(self.format.to_bytes(4, "big"))
        h.update(self.chunks.to_bytes(4, "big"))
        h.update(self.hash)
        return h.digest()


@dataclass
class _Entry:
    snapshot: Snapshot
    peers: set = field(default_factory=set)


class SnapshotPool:
    """Tracks discovered snapshots and which peers have them (reference:
    statesync/snapshots.go:55 snapshotPool)."""

    def __init__(self) -> None:
        self._entries: dict[bytes, _Entry] = {}
        self._rejected: set[bytes] = set()
        self._rejected_formats: set[int] = set()
        self._rejected_peers: set[str] = set()
        self._mtx = threading.Lock()

    def add(self, peer_id: str, snapshot: Snapshot) -> bool:
        """Returns True when this snapshot is new (reference:
        snapshots.go:93 Add)."""
        key = snapshot.key()
        with self._mtx:
            if (key in self._rejected or snapshot.format in self._rejected_formats
                    or peer_id in self._rejected_peers):
                return False
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = _Entry(snapshot, {peer_id})
                return True
            e.peers.add(peer_id)
            return False

    def best(self) -> Snapshot | None:
        """Highest height wins, then newest format (reference:
        snapshots.go:165 Best)."""
        ranked = self.ranked()
        return ranked[0] if ranked else None

    def ranked(self) -> list[Snapshot]:
        with self._mtx:
            entries = [e for e in self._entries.values() if e.peers]
            entries.sort(key=lambda e: (-e.snapshot.height, -e.snapshot.format))
            return [e.snapshot for e in entries]

    def peers_of(self, snapshot: Snapshot) -> list[str]:
        with self._mtx:
            e = self._entries.get(snapshot.key())
            return sorted(e.peers) if e else []

    def reject(self, snapshot: Snapshot) -> None:
        """reference: snapshots.go:205 Reject."""
        with self._mtx:
            key = snapshot.key()
            self._rejected.add(key)
            self._entries.pop(key, None)

    def reject_format(self, fmt: int) -> None:
        """reference: snapshots.go:215 RejectFormat."""
        with self._mtx:
            self._rejected_formats.add(fmt)
            for key in [k for k, e in self._entries.items()
                        if e.snapshot.format == fmt]:
                del self._entries[key]

    def reject_peer(self, peer_id: str) -> None:
        """reference: snapshots.go:226 RejectPeer."""
        with self._mtx:
            self._rejected_peers.add(peer_id)
            self._remove_peer_locked(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._remove_peer_locked(peer_id)

    def _remove_peer_locked(self, peer_id: str) -> None:
        for key in list(self._entries):
            e = self._entries[key]
            e.peers.discard(peer_id)
            if not e.peers:
                del self._entries[key]
