"""Prometheus metrics: registry, Counter/Gauge/Histogram, text exposition,
and the scrape endpoint (reference: the per-subsystem metrics.go files +
node/node.go:1219 startPrometheusServer).

Pure-stdlib implementation of the Prometheus text format v0.0.4 — no
client library is baked into the image, and the format is trivial.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _Metric:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = labels
        self._values: dict[tuple, float] = {}
        self._mtx = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        return tuple(str(labels.get(n, "")) for n in self.label_names)

    def _fmt_labels(self, key: tuple) -> str:
        if not self.label_names:
            return ""
        inner = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(self.label_names, key))
        return "{" + inner + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class Counter(_Metric):
    TYPE = "counter"

    def add(self, delta: float = 1.0, **labels) -> None:
        if delta < 0:
            raise ValueError("counters only go up")
        k = self._key(labels)
        with self._mtx:
            self._values[k] = self._values.get(k, 0.0) + delta

    def expose(self) -> list[str]:
        with self._mtx:
            return [f"{self.name}{self._fmt_labels(k)} {v}"
                    for k, v in sorted(self._values.items())]


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._mtx:
            self._values[self._key(labels)] = float(value)

    def remove(self, **labels) -> None:
        """Drop one labeled series entirely (per-peer gauges must not
        leave a permanent exposition line per identity ever seen)."""
        with self._mtx:
            self._values.pop(self._key(labels), None)

    def add(self, delta: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._mtx:
            self._values[k] = self._values.get(k, 0.0) + delta

    def expose(self) -> list[str]:
        with self._mtx:
            return [f"{self.name}{self._fmt_labels(k)} {v}"
                    for k, v in sorted(self._values.items())]


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_, labels, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        k = self._key(labels)
        with self._mtx:
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def seed(self, **labels) -> None:
        """Pre-seed a labeled series at zero observations, so a healthy
        node scrapes explicit `_bucket`/`_sum`/`_count` zeros instead of an
        absent metric — the histogram twin of the Counter.add(0) discipline
        (tmlint metrics-discipline)."""
        k = self._key(labels)
        with self._mtx:
            self._counts.setdefault(k, [0] * len(self.buckets))
            self._sums.setdefault(k, 0.0)
            self._totals.setdefault(k, 0)

    def expose(self) -> list[str]:
        out = []
        with self._mtx:
            for k, counts in sorted(self._counts.items()):
                base = dict(zip(self.label_names, k))
                for i, ub in enumerate(self.buckets):
                    lk = self._fmt_labels(tuple(list(k)))
                    labels = (lk[:-1] + "," if lk else "{") + f'le="{ub}"' + "}"
                    out.append(f"{self.name}_bucket{labels} {counts[i]}")
                lk = self._fmt_labels(k)
                inf_labels = (lk[:-1] + "," if lk else "{") + 'le="+Inf"}'
                out.append(f"{self.name}_bucket{inf_labels} {self._totals[k]}")
                out.append(f"{self.name}_sum{lk} {self._sums[k]}")
                out.append(f"{self.name}_count{lk} {self._totals[k]}")
        return out


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: list[_Metric] = []
        self._mtx = threading.Lock()

    def _register(self, cls, subsystem: str, name: str, help_: str,
                  labels: tuple[str, ...] = (), **kw):
        full = "_".join(p for p in (self.namespace, subsystem, name) if p)
        m = cls(full, help_, labels, **kw)
        with self._mtx:
            self._metrics.append(m)
        return m

    def counter(self, subsystem, name, help_="", labels=()) -> Counter:
        return self._register(Counter, subsystem, name, help_, tuple(labels))

    def gauge(self, subsystem, name, help_="", labels=()) -> Gauge:
        return self._register(Gauge, subsystem, name, help_, tuple(labels))

    def histogram(self, subsystem, name, help_="", labels=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, subsystem, name, help_, tuple(labels),
                              buckets=buckets)

    def expose(self) -> str:
        lines = []
        with self._mtx:
            metrics = list(self._metrics)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


# --- per-subsystem metric structs (reference: */metrics.go) -----------------


class NodeMetrics:
    """The metric set every node exposes (reference: consensus/metrics.go:11,
    mempool/metrics.go, p2p/metrics.go, state/metrics.go)."""

    def __init__(self, registry: Registry | None = None):
        r = registry if registry is not None else Registry()
        self.registry = r
        # consensus
        self.height = r.gauge("consensus", "height", "Height of the chain.")
        self.rounds = r.gauge("consensus", "rounds", "Number of rounds.")
        self.validators = r.gauge("consensus", "validators", "Number of validators.")
        self.validators_power = r.gauge(
            "consensus", "validators_power", "Total power of all validators.")
        self.missing_validators = r.gauge(
            "consensus", "missing_validators", "Validators missing from the last commit.")
        self.byzantine_validators = r.gauge(
            "consensus", "byzantine_validators", "Validators who tried to double sign.")
        self.block_interval_seconds = r.histogram(
            "consensus", "block_interval_seconds",
            "Time between this and the last block.",
            buckets=(0.1, 0.25, 0.5, 1, 2, 3, 5, 10, 30))
        self.num_txs = r.gauge("consensus", "num_txs", "Number of transactions.")
        self.block_size_bytes = r.gauge(
            "consensus", "total_txs", "Size of the latest block (bytes).")
        self.total_txs = r.counter(
            "consensus", "committed_txs", "Total transactions committed.")
        self.step_duration = r.histogram(
            "consensus", "step_duration_seconds", "Time spent per step.",
            labels=("step",),
            buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1, 5))
        # flight-recorder phase mirror (utils/trace.py, docs/OBSERVABILITY
        # .md): Tracer._append observes every MIRRORED_SPANS span here, so
        # phase attribution is scrapeable without the TMTPU_TRACE ring
        self.trace_phase_seconds = r.histogram(
            "trace", "phase_seconds",
            "Flight-recorder span durations by phase (utils/trace.py "
            "MIRRORED_SPANS).", labels=("phase",),
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1))
        self.batch_verify_seconds = r.histogram(
            "consensus", "batch_verify_seconds",
            "Latency of batched signature verification flushes (TPU-path).",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1))
        self.batch_verify_sigs = r.counter(
            "consensus", "batch_verify_sigs_total",
            "Signatures verified through the batch verifier.")
        self.verify_sharded = r.counter(  # tmlint: disable=metrics-discipline
            "consensus", "verify_sharded_total",
            "Batch-verify dispatches routed through the multi-device "
            "shard_map mesh (parallel/batch_shard).", labels=("devices",))
        # (devices label = mesh size at dispatch time; metrics.py cannot
        # know it without importing jax, and a devices="" dummy series
        # would poison the per-size sums test_multichip asserts on)
        self.sigcache_hits = r.counter(
            "crypto", "sigcache_hits_total",
            "Vote-drain signature verifications skipped via the verified-"
            "signature cache (crypto/sigcache).")
        self.sigcache_misses = r.counter(
            "crypto", "sigcache_misses_total",
            "Vote-drain signature cache misses (verification paid).")
        # state
        self.block_processing_time = r.histogram(
            "state", "block_processing_time",
            "Time spent processing a block (ApplyBlock).",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2, 5))
        # batched execution plane (state/execution.py, docs/EXECUTION.md)
        self.deliver_batch_size = r.histogram(
            "abci", "deliver_batch_size",
            "Txs per batched DeliverTx chunk dispatch through the shared "
            "deliver engine (state/execution.py deliver_block_txs).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048))
        self.abci_deliver_tx_invalid_total = r.counter(
            "abci", "deliver_tx_invalid_total",
            "DeliverTx responses with a non-OK code in applied blocks "
            "(txs that were committed but rejected by the app).")
        # mempool
        self.mempool_size = r.gauge("mempool", "size", "Number of uncommitted txs.")
        self.mempool_failed_txs = r.counter("mempool", "failed_txs", "Rejected txs.")
        # tx ingestion front door (mempool/ingest.py, docs/INGEST.md)
        self.ingest_batch_size = r.histogram(
            "mempool", "ingest_batch_size",
            "Txs per batched CheckTx dispatch through the ingest front "
            "door (mempool check_tx_batch).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        self.ingest_coalesced = r.counter(
            "mempool", "ingest_coalesced_total",
            "Txs that shared an ingest batch with at least one other "
            "concurrent submission (the coalescer's win counter).")
        self.ingest_txs = r.counter(
            "mempool", "ingest_txs_total",
            "Front-door tx admissions by result: ok / reject (CheckTx or "
            "mempool verdict) / shed (the rpc_tx admission gate).",
            labels=("result",))
        # evidence plane (evidence/reactor.py hardening, docs/BYZANTINE.md):
        # the reason label universe is the closed EvidenceError.REASONS
        # set, fully pre-seeded below
        self.evidence_rejected = r.counter(
            "evidence", "rejected_total",
            "Gossiped evidence rejected before pooling (scored against "
            "the delivering peer), by rejection reason.",
            labels=("reason",))
        # p2p
        self.peers = r.gauge("p2p", "peers", "Number of connected peers.")
        self.peer_receive_bytes = r.counter(
            "p2p", "peer_receive_bytes_total", "Bytes received.", labels=("chID",))
        self.peer_send_bytes = r.counter(
            "p2p", "peer_send_bytes_total", "Bytes sent.", labels=("chID",))
        # overload-resilience plane (utils/peerscore.py, docs/OVERLOAD.md)
        self.peer_score = r.gauge(
            "p2p", "peer_score",
            "Decaying per-peer misbehavior score (peerscore board).",
            labels=("peer",))
        self.peers_banned = r.counter(
            "p2p", "peers_banned_total",
            "Peers banned by the misbehavior scoreboard (re-offenses "
            "count again).")
        self.shed = r.counter(
            "p2p", "shed_total",
            "Messages/requests shed under overload, by channel class "
            "(consensus gossip priorities + the rpc_tx admission gate).",
            labels=("channel",))
        self.rate_limited = r.counter(
            "p2p", "rate_limited_total",
            "Inbound messages dropped by per-peer per-channel ceilings.",
            labels=("peer", "channel"))
        # robustness / chaos (no reference analogue: the fault-injection
        # layer, nemesis link plane, device breaker, and stall watchdog
        # are this tree's own; chaos runs must be visible on /metrics)
        self.consensus_stalled = r.gauge(
            "consensus", "stalled",
            "1 while the stall watchdog sees no commit progress.")
        self.watchdog_recoveries = r.counter(
            "consensus", "watchdog_recoveries_total",
            "Stall-watchdog hand-backs to fast-sync catchup.")
        # chaos counters: label sets are bounded by CANONICAL_SITES x the
        # fault-action table, but which (site, action) pairs exist depends
        # on the TMTPU_FAULTS/TMTPU_NEMESIS schedule — series appear when
        # the sampler copies faults.snapshot(), and a chaos-free node
        # correctly scrapes none.
        self.fault_site_hits = r.counter(  # tmlint: disable=metrics-discipline
            "faults", "site_hits_total",
            "Hits at rule-bearing fault sites (utils/faults.py).",
            labels=("site",))
        self.faults_fired = r.counter(  # tmlint: disable=metrics-discipline
            "faults", "fired_total",
            "Fault-rule firings by site and action.",
            labels=("site", "action"))
        self.nemesis_fired = r.counter(  # tmlint: disable=metrics-discipline
            "nemesis", "fired_total",
            "Nemesis link-plane firings by site and action "
            "('cut' = partition).", labels=("site", "action"))
        # self-healing storage plane (store/envelope.py, store/scrub.py,
        # store/repair.py, docs/DURABILITY.md): label universe is the
        # closed store table (envelope.STORES), fully pre-seeded below
        self.store_corruption_detected = r.counter(
            "store", "corruption_detected_total",
            "Store records that failed an integrity check (CRC envelope "
            "or guarded decode), by store.", labels=("store",))
        self.store_corruption_repaired = r.counter(
            "store", "corruption_repaired_total",
            "Corrupt store records healed (peer re-fetch + batch-verified "
            "rewrite, state rebuild, reindex, or quarantine-is-repair).",
            labels=("store",))
        self.store_scrub_runs = r.counter(
            "store", "scrub_runs_total",
            "Completed scrub passes (startup + unsafe_scrub RPC).")
        self.breaker_open = r.gauge(
            "ops", "breaker_open",
            "1 while the kernel's device circuit breaker is open.",
            labels=("kernel",))
        self.breaker_trips = r.gauge(
            "ops", "breaker_trips_total",
            "Lifetime closed->open transitions of the device breaker.",
            labels=("kernel",))
        # pre-seed the unlabeled watchdog + sigcache series so a healthy
        # node scrapes an explicit 0 instead of an absent metric
        self.consensus_stalled.set(0.0)
        self.watchdog_recoveries.add(0.0)
        self.sigcache_hits.add(0.0)
        self.sigcache_misses.add(0.0)
        # ...and the overload counters: a node that never sheds or bans
        # must scrape explicit zeros (dashboards alert on absence)
        self.peers_banned.add(0.0)
        for ch in ("vote", "proposal", "block_part", "rpc_tx"):
            self.shed.add(0.0, channel=ch)
        self.rate_limited.add(0.0, peer="", channel="")
        # ingest front door: the result label universe is closed by
        # construction (docs/INGEST.md), seed it fully; the batch-size
        # histogram scrapes explicit zeros like the phase histogram
        self.ingest_batch_size.seed()
        self.ingest_coalesced.add(0.0)
        self.deliver_batch_size.seed()
        self.abci_deliver_tx_invalid_total.add(0.0)
        for result in ("ok", "reject", "shed"):
            self.ingest_txs.add(0.0, result=result)
        # evidence rejections: closed reason universe (types/evidence.py
        # EvidenceError.REASONS), a node that never sees junk evidence
        # scrapes explicit zeros
        from tendermint_tpu.types.evidence import EvidenceError as _EvErr

        for reason in _EvErr.REASONS:
            self.evidence_rejected.add(0.0, reason=reason)
        # p2p byte counters follow the same convention (chID values are
        # bounded by the node's channel table, first traffic creates them)
        self.peer_receive_bytes.add(0.0, chID="")
        self.peer_send_bytes.add(0.0, chID="")
        # the storage-plane counters' label universe IS envelope.STORES
        from tendermint_tpu.store.envelope import STORES as _stores

        self.store_scrub_runs.add(0.0)
        for store in _stores:
            self.store_corruption_detected.add(0.0, store=store)
            self.store_corruption_repaired.add(0.0, store=store)
        # the device-breaker pair has a two-kernel label universe: seed it
        # fully so "breaker never tripped" is an explicit 0, not absence
        for kernel in ("ed25519", "sr25519"):
            self.breaker_open.set(0.0, kernel=kernel)
            self.breaker_trips.set(0.0, kernel=kernel)
        # the phase histogram's label universe IS trace.MIRRORED_SPANS:
        # seed every series so dashboards see zeros, not absence, and the
        # scrape-shape test can pin the full exposition
        from tendermint_tpu.utils import trace as _tmtrace

        for phase in _tmtrace.MIRRORED_SPANS:
            self.trace_phase_seconds.seed(phase=phase)
        # consensus.step spans mirror into the per-step histogram too
        # (state_machine tags the step NAME); seed the exact universe the
        # machine labels with, so a step added to cstypes cannot drift
        from tendermint_tpu.consensus.cstypes import STEP_NAMES

        for step_name in STEP_NAMES.values():
            self.step_duration.seed(step=step_name)


# Global registry hook for hot paths that have no handle on the node (the
# batch verifier). None until a node enables instrumentation.
GLOBAL_NODE_METRICS: NodeMetrics | None = None


class MetricsServer:
    """reference: node/node.go:1219 startPrometheusServer."""

    def __init__(self, registry: Registry, addr: str):
        host, port = addr.rsplit(":", 1) if ":" in addr else ("", addr)
        registry_ref = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = registry_ref.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), Handler)
        self.addr = f"{self._httpd.server_address[0]}:{self._httpd.server_address[1]}"
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="prometheus", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
