"""Per-node patchable time source (docs/NEMESIS.md clock-skew cookbook).

Every wall-clock read that feeds consensus — proposal/vote/commit
timestamps (`types/ttime.Time.now()`), round-0 scheduling, the timeout
ticker, and evidence-expiry bookkeeping — goes through a `Clock` so a
chaos harness can skew ONE node's notion of time without touching the
host. Two knobs per clock:

- ``skew_s``: a constant offset added to `time.time_ns()` (the classic
  bad-NTP node). Drives the soak `skew:<node>:<±secs>` action.
- ``rate``: a timer-rate multiplier consumed by the consensus ticker —
  a node at rate 2.0 fires its round timeouts twice as fast (its
  crystal runs hot), rate 0.5 half as fast.

Module-level `DEFAULT` is the process clock; `TMTPU_CLOCK_SKEW_S` seeds
its skew so a subprocess testnet node (e2e/runner.py) can be born skewed.
In-process fabric nodes each hold their own `Clock` (node.Node.clock)
threaded through ConsensusState, TimeoutTicker, and EvidencePool, so a
50-node mesh can host mutually skewed clocks in one interpreter.

This module imports nothing from the project (types/ttime.py sits below
it in the layering).
"""

from __future__ import annotations

import os
import time as _time


class Clock:
    """A skewable, rate-adjustable wall clock."""

    def __init__(self, skew_s: float = 0.0, rate: float = 1.0):
        self._skew_ns = int(skew_s * 1e9)
        self.rate = rate if rate > 0 else 1.0

    def set_skew(self, skew_s: float) -> None:
        # single int store: atomic under the GIL, no lock needed even
        # with consensus threads reading concurrently
        self._skew_ns = int(skew_s * 1e9)

    @property
    def skew_s(self) -> float:
        return self._skew_ns / 1e9

    def now_ns(self) -> int:
        return _time.time_ns() + self._skew_ns

    def now_s(self) -> float:
        return self.now_ns() / 1e9

    def timer_duration(self, duration_s: float) -> float:
        """Host-clock seconds a relative timeout of `duration_s` takes on
        this clock (a fast crystal — rate > 1 — fires timeouts early)."""
        return duration_s / self.rate


def _env_skew() -> float:
    raw = os.environ.get("TMTPU_CLOCK_SKEW_S", "")
    if not raw:
        return 0.0
    try:
        return float(raw)
    except ValueError:
        return 0.0


DEFAULT = Clock(skew_s=_env_skew())


def now_ns() -> int:
    """Process-default skewed wall clock (Time.now()'s source)."""
    return DEFAULT.now_ns()
