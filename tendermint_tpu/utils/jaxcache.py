"""Persistent XLA compilation cache.

The batch-verify kernels compile in O(30s) cold (CPU backend is worse); a
node must not pay that on every restart, and the test suite must not pay it
on every run. jax's persistent compilation cache stores serialized
executables keyed by HLO fingerprint; enabling it makes every compile after
the first process-lifetime instantaneous.

Called from ops/ed25519_batch import (any process that might touch a kernel)
and from tests/conftest.py. No-op if the user set their own cache config or
TM_TPU_JAX_CACHE=0.
"""

from __future__ import annotations

import os

_done = False


def enable() -> None:
    global _done
    if _done:
        return
    _done = True
    if os.environ.get("TM_TPU_JAX_CACHE", "1") == "0":
        return
    import jax

    if jax.config.jax_compilation_cache_dir:
        return  # user already configured one
    cache_dir = os.environ.get(
        "TM_TPU_JAX_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "tendermint_tpu", "jax"),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 - cache is an optimization, never fatal
        pass
