"""Consensus flight recorder: per-node causal span tracing (SURVEY aux #36).

The reference exposes pprof + Prometheus step histograms; a TPU build also
needs to ATTRIBUTE the ~104 ms host<->device sync floor (ROADMAP item 1):
of a decision's wall time, how much was host prep, queue wait, device
compute, readback, and bitmap replay — and WHERE in the block lifecycle a
stalled node last made progress.

Three layers:

 - :class:`Tracer` — an instance-scoped bounded ring of :class:`Span`
   records. One per Node (``node.tracer``): the old module-global ring
   interleaved spans from all 50 fabric nodes of an in-process mesh.
   Spans are CAUSAL: nested ``span()`` regions on one thread link
   parent/child ids, and a ``height=`` tag set by an enclosing span is
   inherited by its children (``current_height``), so the deferred verify
   phases dispatched inside a vote-drain span land on the right height.
 - the module-level functions: ``span()/mark()/record()`` delegate to the
   thread's ACTIVE tracer (``Tracer.activate()``), falling back to the
   process :data:`DEFAULT` tracer; ``dump()/summarize()/enable()`` always
   address DEFAULT (the pre-flight-recorder API surface — draining a
   node's ring goes through ``node.tracer``/``unsafe_trace``). Hot call
   sites guard on the module attribute :data:`ENABLED` (true while ANY
   tracer is enabled), so the disabled path costs one attribute load
   (tests/test_trace.py gates this).
 - consumers: ``Tracer.timeline(height)`` assembles the structured
   per-height block lifecycle (docs/OBSERVABILITY.md schema; served by the
   ``unsafe_timeline`` RPC route), ``last_phase()`` feeds the soak
   auditor's stall annotations, and spans named in :data:`MIRRORED_SPANS`
   are mirrored into the pre-seeded ``trace_phase_seconds`` histogram.

Knobs: ``TMTPU_TRACE=1`` enables every node's tracer at construction;
``TMTPU_TRACE_CAP`` sets the per-tracer ring size (default 4096);
``TMTPU_TRACE_XPROF=<dir>`` makes bench.py wrap its instrumented
attribution pass in :func:`jax_profile` (TensorBoard/xprof traces).
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from dataclasses import dataclass

DEFAULT_CAP = 4096

# ---------------------------------------------------------------------------
# Canonical span table (tmlint rule `trace-span-discipline`): every span
# name used by trace.span()/mark()/record() in production code must be a
# key here AND documented in docs/OBSERVABILITY.md — ad-hoc span strings
# drift from the doc and break timeline/dashboard consumers.
# ---------------------------------------------------------------------------

CANONICAL_SPANS = {
    # consensus block lifecycle (marks; once per committed single-round
    # height — the `unsafe_timeline` LIFECYCLE set, in causal order)
    "consensus.proposal": "proposal accepted onto the round state",
    "consensus.block_parts": "proposal part-set completed (block assembled)",
    "consensus.precommit": "entered the precommit step",
    "consensus.commit": "entered commit (+2/3 precommits on a block)",
    "consensus.store_save": "block + seen commit persisted (span)",
    "consensus.abci_apply": "ABCI BeginBlock..Commit of the decided block (span)",
    # consensus timing
    "consensus.step": "time spent in the round step just left",
    "consensus.vote_drain": "batched peer-vote drain: build + dispatch",
    # deferred verify pipeline phases (crypto/batch.py; the sync-floor
    # attribution ROADMAP item 1 needs)
    "verify.host_prep": "host prep + kernel dispatch (ops dispatch_batch)",
    "verify.queue": "dispatch()->resolve() queue wait of a PendingVerify",
    "verify.coalesce": "verify-service shared launch marker (requests/sigs "
                       "coalesced into one kernel launch)",
    "verify.device": "device compute (bench attribution pass only)",
    "verify.readback": "blocking D2H fetch (crypto/batch._device_get)",
    "verify.replay": "bitmap fetch -> serial accept/reject replay",
    "verify.shard_dispatch": "multi-device shard_map dispatch (parallel/batch_shard)",
    # fast-sync verify-ahead (blockchain/pipeline.py)
    "fastsync.dispatch": "speculative commit-verify dispatch for one height",
    "fastsync.apply": "block save + ABCI apply of a fast-synced height",
    # tx front door + gossip plane
    "mempool.check_tx": "ABCI CheckTx round trip of one tx",
    "mempool.ingest_batch": "one batched ABCI CheckTxBatch dispatch of the "
                            "ingest front door (span; n= txs)",
    "mempool.ingest_coalesce": "ingest coalescer shared-batch marker "
                               "(requests= txs per batch)",
    "mempool.ingest_wait": "submit->resolve wait of one tx through the "
                           "ingest coalescer",
    "p2p.send": "message queued to a peer channel (mark)",
    "p2p.recv": "message delivered to a reactor (span over on_receive)",
    # batched execution plane (state/execution.py, docs/EXECUTION.md)
    "abci.deliver_txs": "all DeliverTx work of one block through the "
                        "shared deliver engine (span; n= txs)",
    "abci.deliver_batch": "one batched ABCI DeliverTxBatch chunk dispatch "
                          "(span; n= txs)",
    "apply.post_commit": "post-commit event publish of one height on the "
                         "async worker (span; height= tag)",
    # self-healing storage plane (store/scrub.py, store/repair.py)
    "store.scrub": "one integrity-scrub pass over a node's stores (span)",
    "store.repair": "peer re-fetch + batch-verified rewrite of one damaged "
                    "height (span; height= tag)",
    # light-client serving gateway (light/gateway.py, docs/LIGHT.md)
    "light.gateway.serve": "one client query through the gateway: cache "
                           "lookup, coalesced verification, answer or "
                           "typed refusal (span; height= tag)",
    "light.gateway.fetch": "one provider fetch attempt, including retries "
                           "(span; provider= tag)",
    "light.gateway.hedge": "hedged secondary fired after the primary "
                           "exceeded the latency budget (mark)",
}

# Spans mirrored into the pre-seeded `trace_phase_seconds{phase=}`
# histogram (utils/metrics.py NodeMetrics). Bounded label universe by
# construction — this tuple IS the label set.
MIRRORED_SPANS = (
    "verify.host_prep", "verify.queue", "verify.readback", "verify.replay",
    "verify.shard_dispatch", "consensus.vote_drain", "consensus.store_save",
    "consensus.abci_apply", "mempool.check_tx", "mempool.ingest_batch",
    "mempool.ingest_wait", "abci.deliver_txs", "abci.deliver_batch",
    "apply.post_commit",
)
_MIRROR_SET = frozenset(MIRRORED_SPANS)

# The deterministic per-committed-height lifecycle marks, in causal order
# (a healthy single-round height emits each exactly once; the timeline's
# causal_ok verdict checks first-occurrence order against this).
LIFECYCLE = (
    "consensus.proposal", "consensus.block_parts", "consensus.precommit",
    "consensus.commit", "consensus.store_save", "consensus.abci_apply",
)


def trace_cap(default: int = DEFAULT_CAP) -> int:
    """Per-tracer ring capacity; TMTPU_TRACE_CAP overrides."""
    v = os.environ.get("TMTPU_TRACE_CAP")
    try:
        return max(16, int(v)) if v else default
    except ValueError:
        return default


def trace_enabled_from_env() -> bool:
    """TMTPU_TRACE=1: nodes enable their tracer at construction."""
    return os.environ.get("TMTPU_TRACE") == "1"


@dataclass
class Span:
    name: str
    start: float        # time.monotonic() at entry
    duration_s: float
    tags: dict
    span_id: int = 0
    parent_id: int = 0  # 0 = root (no enclosing span on that thread)

    def as_dict(self) -> dict:
        return {"name": self.name, "start": self.start,
                "duration_s": self.duration_s, "span_id": self.span_id,
                "parent_id": self.parent_id, "tags": dict(self.tags)}


# ANY tracer enabled — THE one-attribute-load guard hot call sites check
# before building a span. Maintained by Tracer.enable()/disable().
ENABLED = False
_enabled_count = 0
_state_mtx = threading.Lock()

# thread-local active tracer (Tracer.activate()); current() falls back to
# the process DEFAULT so the module-level API keeps its old semantics
_tl = threading.local()


class Tracer:
    """One bounded span ring + causality bookkeeping. Thread-safe: spans
    may complete on any thread; parent/height context is per-thread."""

    def __init__(self, name: str = "", cap: int | None = None,
                 enabled: bool = False):
        self.name = name
        self.enabled = False
        self.cap = cap if cap is not None else trace_cap()
        from collections import deque

        self._spans: "deque[Span]" = deque(maxlen=self.cap)
        self._mtx = threading.Lock()
        self._seq = itertools.count(1)
        self._ctx = threading.local()  # per-thread parent/height stacks
        if enabled:
            self.enable()

    # --- enable/disable (keeps the module ENABLED guard honest) ------------

    def enable(self) -> None:
        global ENABLED, _enabled_count
        with _state_mtx:
            if not self.enabled:
                self.enabled = True
                _enabled_count += 1
                ENABLED = True

    def disable(self) -> None:
        global ENABLED, _enabled_count
        with _state_mtx:
            if self.enabled:
                self.enabled = False
                _enabled_count -= 1
                ENABLED = _enabled_count > 0

    # --- thread-local activation -------------------------------------------

    @contextlib.contextmanager
    def activate(self):
        """Make this tracer the thread's `current()` target, so library
        layers (crypto/batch, parallel/batch_shard) record into the node
        whose work they are doing without constructor plumbing."""
        prev = getattr(_tl, "tracer", None)
        _tl.tracer = self
        try:
            yield self
        finally:
            _tl.tracer = prev

    # --- recording ----------------------------------------------------------

    def _stacks(self):
        c = self._ctx
        if not hasattr(c, "parents"):
            c.parents = []
            c.heights = []
        return c

    def current_height(self):
        """Innermost height= tag of the enclosing span stack, or None."""
        c = self._stacks()
        return c.heights[-1] if c.heights else None

    @contextlib.contextmanager
    def span(self, name: str, **tags):
        """Timed causal region. Children started on this thread inside the
        region get this span as parent and inherit its height tag."""
        if not self.enabled:
            yield 0
            return
        c = self._stacks()
        sid = next(self._seq)
        h = tags.get("height")
        if h is None and c.heights:
            tags["height"] = h = c.heights[-1]
        parent = c.parents[-1] if c.parents else 0
        c.parents.append(sid)
        if h is not None:
            c.heights.append(h)
        t0 = time.monotonic()
        try:
            yield sid
        finally:
            dur = time.monotonic() - t0
            c.parents.pop()
            if h is not None:
                c.heights.pop()
            self._append(Span(name, t0, dur, tags, sid, parent))

    def mark(self, name: str, **tags) -> None:
        """Zero-duration lifecycle event."""
        if not self.enabled:
            return
        c = self._stacks()
        if "height" not in tags and c.heights:
            tags["height"] = c.heights[-1]
        parent = c.parents[-1] if c.parents else 0
        self._append(Span(name, time.monotonic(), 0.0, tags,
                          next(self._seq), parent))

    def record(self, name: str, duration_s: float, **tags) -> None:
        """An externally-timed span (e.g. a queue wait measured between
        two events)."""
        if not self.enabled:
            return
        c = self._stacks()
        if "height" not in tags and c.heights:
            tags["height"] = c.heights[-1]
        parent = c.parents[-1] if c.parents else 0
        self._append(Span(name, time.monotonic() - duration_s, duration_s,
                          tags, next(self._seq), parent))

    def _append(self, s: Span) -> None:
        with self._mtx:
            self._spans.append(s)
        if s.name in _MIRROR_SET or s.name == "consensus.step":
            # metric mirror OUTSIDE the ring lock (lock-held-call
            # discipline); lazy import breaks the metrics<->trace cycle
            from tendermint_tpu.utils import metrics as tmmetrics

            m = tmmetrics.GLOBAL_NODE_METRICS
            if m is None:
                return
            if s.name == "consensus.step":
                # the per-step histogram the reference ships
                # (consensus/metrics.go StepDuration); step tag = step name
                m.step_duration.observe(s.duration_s,
                                        step=str(s.tags.get("step", "")))
            else:
                m.trace_phase_seconds.observe(s.duration_s, phase=s.name)

    # --- draining ------------------------------------------------------------

    def dump(self, clear: bool = False) -> list[Span]:
        with self._mtx:
            out = list(self._spans)
            if clear:
                self._spans.clear()
        return out

    def clear(self) -> None:
        with self._mtx:
            self._spans.clear()

    # deliberately NO __len__: an empty ring must not make the tracer
    # falsy (`tracer or DEFAULT` fallbacks would silently misroute spans)
    def size(self) -> int:
        with self._mtx:
            return len(self._spans)

    def summarize(self) -> dict[str, dict]:
        """name -> {count, total_s, max_s} aggregation."""
        agg: dict[str, dict] = {}
        for s in self.dump():
            a = agg.setdefault(s.name, {"count": 0, "total_s": 0.0,
                                        "max_s": 0.0})
            a["count"] += 1
            a["total_s"] += s.duration_s
            a["max_s"] = max(a["max_s"], s.duration_s)
        return agg

    def last_phase(self) -> dict | None:
        """The most recently COMPLETED span — what a stalled node was last
        able to finish (the soak auditor's stall annotation)."""
        with self._mtx:
            if not self._spans:
                return None
            s = self._spans[-1]
        return {"name": s.name, "height": s.tags.get("height"),
                "round": s.tags.get("round"),
                "age_s": max(0.0, time.monotonic() - (s.start + s.duration_s))}

    def timeline(self, height: int) -> dict:
        """The structured per-height lifecycle (docs/OBSERVABILITY.md):
        every span tagged with this height, start-ordered, plus the
        LIFECYCLE mark census and a causal-order verdict."""
        spans = [s for s in self.dump() if s.tags.get("height") == height]
        spans.sort(key=lambda s: (s.start, s.span_id))
        counts: dict[str, int] = {}
        first_start: dict[str, float] = {}
        phases: dict[str, dict] = {}
        for s in spans:
            counts[s.name] = counts.get(s.name, 0) + 1
            first_start.setdefault(s.name, s.start)
            p = phases.setdefault(s.name, {"count": 0, "total_s": 0.0})
            p["count"] += 1
            p["total_s"] += s.duration_s
        present = [n for n in LIFECYCLE if n in counts]
        starts = [first_start[n] for n in present]
        causal_ok = all(a <= b for a, b in zip(starts, starts[1:]))
        return {
            "node": self.name,
            "height": height,
            "spans": [s.as_dict() for s in spans],
            "lifecycle": {n: counts.get(n, 0) for n in LIFECYCLE},
            "lifecycle_complete": len(present) == len(LIFECYCLE),
            "causal_ok": causal_ok,
            "phases": phases,
        }

    def describe(self) -> dict:
        return {"name": self.name, "enabled": self.enabled, "cap": self.cap,
                "spans": self.size()}


# The process-default tracer: the module-level API's fallback target, and
# what standalone harnesses (bench, tests) use without building a Node.
DEFAULT = Tracer(name="default")


def current() -> Tracer:
    """The thread's active tracer (Tracer.activate()), else DEFAULT."""
    t = getattr(_tl, "tracer", None)
    return DEFAULT if t is None else t


# --- module-level delegates (the pre-flight-recorder API surface) -----------


def enable() -> None:
    DEFAULT.enable()


def disable() -> None:
    DEFAULT.disable()


def enabled() -> bool:
    return DEFAULT.enabled


def span(name: str, **tags):
    return current().span(name, **tags)


def mark(name: str, **tags) -> None:
    current().mark(name, **tags)


def record(name: str, duration_s: float, **tags) -> None:
    current().record(name, duration_s, **tags)


def dump(clear: bool = False) -> list[Span]:
    return DEFAULT.dump(clear=clear)


def summarize() -> dict[str, dict]:
    return DEFAULT.summarize()


@contextlib.contextmanager
def jax_profile(log_dir: str):
    """Device-side profiling via jax.profiler (xprof traces; open the
    written directory in TensorBoard — recipe in docs/OBSERVABILITY.md)."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
