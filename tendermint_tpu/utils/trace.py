"""Tracing/profiling harness (SURVEY aux #36).

The reference exposes pprof + Prometheus step histograms; a TPU build also
needs (a) lightweight host-side span tracing around consensus transitions
and verify flushes, and (b) a JAX device profiler hook for kernel work.

 - span(name): context manager recording wall-time spans into a bounded
   in-memory ring (enable() first; disabled spans cost one dict lookup).
 - jax_profile(dir): wraps jax.profiler.trace when JAX is importable --
   traces written there open in TensorBoard / xprof.
 - dump(): drain the ring for RPC debug dumps or test assertions.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass

_MAX_SPANS = 4096


@dataclass
class Span:
    name: str
    start: float
    duration_s: float
    tags: dict


_enabled = False
_spans: deque = deque(maxlen=_MAX_SPANS)
_mtx = threading.Lock()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def span(name: str, **tags):
    if not _enabled:
        yield
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        with _mtx:
            _spans.append(Span(name, t0, time.monotonic() - t0, tags))


def record(name: str, duration_s: float, **tags) -> None:
    """Record an externally-timed span (e.g. a kernel wall time)."""
    if not _enabled:
        return
    with _mtx:
        _spans.append(Span(name, time.monotonic() - duration_s, duration_s, tags))


def dump(clear: bool = False) -> list[Span]:
    with _mtx:
        out = list(_spans)
        if clear:
            _spans.clear()
    return out


def summarize() -> dict[str, dict]:
    """name -> {count, total_s, max_s} aggregation."""
    agg: dict[str, dict] = {}
    for s in dump():
        a = agg.setdefault(s.name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        a["count"] += 1
        a["total_s"] += s.duration_s
        a["max_s"] = max(a["max_s"], s.duration_s)
    return agg


@contextlib.contextmanager
def jax_profile(log_dir: str):
    """Device-side profiling via jax.profiler (xprof traces)."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
