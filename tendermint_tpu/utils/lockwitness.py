"""Runtime lock-order witness — the dynamic half of tmlint's `lock-order`
rule (tools/tmlint, docs/LINT.md).

The static rule sees the acquisition graph it can resolve; this witness
sees the one that actually happens. With ``TMTPU_LOCKWITNESS=1`` (or an
explicit :func:`install`), ``threading.Lock``/``threading.RLock`` are
replaced by recording wrappers. Every acquisition appends to a
thread-local held stack; holding A while acquiring B records the directed
edge A→B in a global site graph (locks are keyed by their CREATION site,
``file:line``, so per-peer/per-conn instances aggregate instead of
exploding the graph). At teardown :func:`assert_acyclic` fails the test
with the full cycle if two code paths ever took the same pair of lock
sites in opposite orders — the classic latent deadlock that static
analysis can miss and a lucky interleaving never trips.

Overhead is bounded: O(held-stack depth) per acquire (depth is asserted
small), edges capped at :data:`MAX_EDGES` (hitting the cap flips
``truncated``, which the scenario tests also assert against). The witness
is test-tooling: production never enables it.

Used by the in-process mesh scenarios (tests/test_nemesis.py partition/
heal smoke, tests/test_overload.py flood smoke) via::

    with lockwitness.witness() as w:
        ... run the scenario ...
    # exiting asserts the runtime acquisition graph stayed acyclic

Because Python resolves ``Lock``/``RLock``/``Condition``/``Event`` (and
``queue.Queue``'s internals) through the ``threading`` module namespace at
call time, installing the wrapper factories covers stdlib-composed
primitives too. Locks created BEFORE install (module-level singletons)
stay plain — the scenarios construct their nodes after install, which is
where the cross-object ordering lives.
"""

from __future__ import annotations

import _thread
import os
import sys
import threading

# Originals, captured at import so install/uninstall round-trips even if
# something else also patched threading.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

MAX_EDGES = 8192
MAX_DEPTH = 16


class Witness:
    """The global acquisition-order graph + per-thread held stacks."""

    def __init__(self) -> None:
        # raw C lock: the witness must never recurse into itself
        self._g = _thread.allocate_lock()
        self._tls = threading.local()
        self.enabled = False
        self.reset()

    def reset(self) -> None:
        with self._g:
            # (site_a, site_b) -> (thread name, count) — first-seen owner
            self.edges: dict = {}
            self.acquires = 0
            self.max_depth = 0
            self.truncated = False

    # -- recording ----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquire(self, site: str, inst: int) -> None:
        if not self.enabled:
            return
        st = self._stack()
        new_edges = []
        for held_site, held_inst in st:
            if held_inst == inst:
                continue  # reentrant RLock re-acquire: not an ordering
            # held_site == site is NOT skipped: two instances from the
            # same creation site taken nested (peer A's lock held while
            # taking peer B's) is the classic opposite-order hazard,
            # recorded as a self-edge on the site
            new_edges.append((held_site, site))
        st.append((site, inst))
        with self._g:
            self.acquires += 1
            if len(st) > self.max_depth:
                self.max_depth = len(st)
            for e in new_edges:
                if e not in self.edges:
                    if len(self.edges) >= MAX_EDGES:
                        self.truncated = True
                        break
                    self.edges[e] = (threading.current_thread().name, 1)
                else:
                    name, n = self.edges[e]
                    self.edges[e] = (name, n + 1)

    def note_release(self, site: str, inst: int) -> None:
        if not self.enabled:
            return
        st = getattr(self._tls, "stack", None)
        if not st:
            return
        # locks are *usually* released LIFO but nothing enforces it
        for i in range(len(st) - 1, -1, -1):
            if st[i] == (site, inst):
                del st[i]
                return

    def drop_instance(self, site: str, inst: int) -> int:
        """Remove every held entry for one instance (Condition.wait's full
        RLock release); returns how many were held."""
        if not self.enabled:
            return 0
        st = getattr(self._tls, "stack", None)
        if not st:
            return 0
        n = len(st)
        st[:] = [e for e in st if e != (site, inst)]
        return n - len(st)

    def restore_instance(self, site: str, inst: int, count: int) -> None:
        for _ in range(count):
            self.note_acquire(site, inst)

    # -- verdicts -----------------------------------------------------------

    def cycles(self) -> list[list[str]]:
        """Site-graph cycles, each as [a, b, ..., a]. Self-edges (same
        creation site, different instances, nested) count: they are the
        two-peers-in-opposite-order hazard."""
        with self._g:
            edges = list(self.edges)
        graph: dict = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        out: list[list[str]] = []
        # self-edges first
        for a, b in sorted(edges):
            if a == b:
                out.append([a, a])
        # DFS cycle detection with path recovery
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {v: WHITE for v in graph}
        path: list[str] = []

        def dfs(v) -> list[str] | None:
            color[v] = GRAY
            path.append(v)
            for w in sorted(graph[v]):
                if w == v:
                    continue
                if color[w] == GRAY:
                    return path[path.index(w):] + [w]
                if color[w] == WHITE:
                    found = dfs(w)
                    if found:
                        return found
            color[v] = BLACK
            path.pop()
            return None

        for v in sorted(graph):
            if color[v] == WHITE:
                found = dfs(v)
                if found:
                    out.append(found)
                    break
        return out

    def assert_acyclic(self) -> None:
        cyc = self.cycles()
        if cyc:
            with self._g:
                detail = {e: self.edges[e] for e in sorted(self.edges)
                          if e[0] in cyc[0] and e[1] in cyc[0]}
            raise AssertionError(
                f"lock-order cycle observed at runtime: "
                f"{' -> '.join(cyc[0])}; edges (first thread, count): "
                f"{detail}")


WITNESS = Witness()


def _site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    fn = f.f_code.co_filename
    # shorten to the interesting tail: pkg/module.py
    parts = fn.replace("\\", "/").split("/")
    return f"{'/'.join(parts[-2:])}:{f.f_lineno}"


class _WitnessLock:
    """threading.Lock stand-in that reports to WITNESS."""

    def __init__(self, inner, site: str):
        self._inner = inner
        self._wsite = site

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            WITNESS.note_acquire(self._wsite, id(self))
        return ok

    def release(self):
        WITNESS.note_release(self._wsite, id(self))
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessLock {self._wsite} of {self._inner!r}>"


class _WitnessRLock(_WitnessLock):
    """threading.RLock stand-in; implements the Condition protocol hooks
    (_release_save / _acquire_restore / _is_owned) by delegation so
    Condition(RLock()) keeps exact semantics under the witness."""

    def _release_save(self):
        state = self._inner._release_save()
        count = WITNESS.drop_instance(self._wsite, id(self))
        return (state, count)

    def _acquire_restore(self, saved):
        state, count = saved
        self._inner._acquire_restore(state)
        WITNESS.restore_instance(self._wsite, id(self), count)

    def _is_owned(self):
        return self._inner._is_owned()

    def locked(self):  # RLock has no .locked() pre-3.12; mirror if present
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked is not None else False


def _lock_factory():
    return _WitnessLock(_REAL_LOCK(), _site())


def _rlock_factory():
    return _WitnessRLock(_REAL_RLOCK(), _site())


def install() -> None:
    """Patch threading.Lock/RLock with witness factories and start
    recording. Idempotent."""
    WITNESS.enabled = True
    if threading.Lock is not _lock_factory:
        threading.Lock = _lock_factory
        threading.RLock = _rlock_factory


def uninstall() -> None:
    """Restore the real factories and stop recording. Wrapper locks
    created while installed keep working (their note_* calls become
    no-ops once disabled)."""
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    WITNESS.enabled = False


def install_from_env() -> None:
    if os.environ.get("TMTPU_LOCKWITNESS") == "1":
        install()


class witness:
    """Context manager for scenario tests::

        with lockwitness.witness() as w:
            ...
        # exit asserts acyclic + bounded overhead (unless the body raised)
    """

    def __init__(self, assert_on_exit: bool = True):
        self.assert_on_exit = assert_on_exit
        self._was_enabled = False

    def __enter__(self) -> Witness:
        # Nest cleanly inside a session-wide TMTPU_LOCKWITNESS=1 sweep:
        # keep its accumulated edges and leave it recording on exit
        # (asserting over the superset is strictly stronger).
        self._was_enabled = WITNESS.enabled
        if not self._was_enabled:
            WITNESS.reset()
        install()
        return WITNESS

    def __exit__(self, exc_type, exc, tb) -> bool:
        if not self._was_enabled:
            uninstall()
        if exc_type is None and self.assert_on_exit:
            WITNESS.assert_acyclic()
            assert not WITNESS.truncated, (
                f"lock witness edge graph truncated at {MAX_EDGES} edges")
            assert WITNESS.max_depth <= MAX_DEPTH, (
                f"held-lock stack reached depth {WITNESS.max_depth} "
                f"(> {MAX_DEPTH}): lock nesting is out of hand")
        return False
