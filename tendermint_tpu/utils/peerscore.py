"""Peer misbehavior scoring, timed bans, ingress rate ceilings, and
priority load shedding — the overload-resilience plane (docs/OVERLOAD.md).

The reference's only sanction is StopPeerForError (p2p/switch.go), a
disconnect the peer undoes by redialing. With every hot path funneled into
one shared batched-verify resource, a single flooding or byzantine peer
can saturate the kernel, the drain queues, and the mempool for the whole
node, so this build adds the three layers PBFT-lineage systems (Castro &
Liskov, OSDI'99) and staged event-driven designs (SEDA, SOSP'01) pair with
a shared resource:

* :class:`PeerScoreBoard` — a decaying per-peer misbehavior score fed from
  every place the node previously just disconnected or silently ignored
  (invalid signatures attributed per-lane out of the batched vote-drain
  bitmap, statesync ``reject_senders``, mempool CheckTx-reject floods,
  oversized/unparseable reactor messages, evil handshakes, rate-limit
  violations). Crossing ``disconnect_score`` disconnects; crossing
  ``ban_score`` bans for ``ban_duration_s``, doubling on each re-offense
  up to ``ban_max_duration_s``. Bans refuse both redials and inbound
  accepts (enforced by Switch/Transport).
* :class:`ChannelRateLimiter` — per-peer per-channel token buckets
  (votes/s, txs/s, chunks/s) enforced in MConnection's recv routine;
  over-limit deliveries are scored, not processed.
* :class:`ShedQueue` — a bounded queue that sheds by priority instead of
  blocking producers: votes for the live height survive, stale-height
  gossip drops first. Gossip threads never block on a saturated consumer.

Scores, bans, sheds, and rate-limit hits surface as ``peer_score``,
``peers_banned_total``, ``shed_total{channel}``, and
``rate_limited_total{peer,channel}`` via the node metrics sampler, and as
the ``unsafe_peers`` RPC view.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass

# Offense -> points (docs/OVERLOAD.md scoring table). Points are sized
# against the default thresholds AND against honest steady-state rates:
# with half-life H the equilibrium score of a sustained offense stream is
# points * rate * H/ln2 (~173*points*rate at the default H=120), so an
# offense an HONEST peer can emit continuously during OUR overload (full
# mempool, app-rejected gossip) must carry points small enough that
# honest rates (~10/s) stay under the disconnect threshold while flood
# rates (100s/s) still cross ban in seconds. Protocol violations honest
# peers never emit (invalid signatures, bad framing, evil handshakes)
# carry full-size points: one evil handshake is half a ban, ~13
# invalid-signature votes inside one half-life is a ban.
OFFENSE_POINTS: dict[str, float] = {
    "invalid_signature": 8.0,   # vote-drain bitmap lane / serial VoteError
    "bad_message": 10.0,        # unparseable / unknown channel / reactor raise
    "oversized_message": 10.0,  # recv_message_capacity exceeded
    "evil_handshake": 50.0,     # claimed id != authenticated key
    "statesync_reject": 30.0,   # app reject_senders verdict on a chunk
    "evidence_reject": 6.0,     # gossiped evidence the pool refused to
                                # verify (bogus sigs / wrong chain-id /
                                # expired / contradicting metadata) —
                                # honest peers verified before pooling, so
                                # sustained rejects are a protocol
                                # violation (evidence/reactor.py)
    "checktx_reject": 0.02,     # gossiped tx the app rejected (honest-rate safe)
    "mempool_full": 0.02,       # gossiping into a full mempool (ours, usually)
    "tx_too_large": 8.0,        # gossiped tx over max_tx_bytes
    "rate_limited": 4.0,        # per-channel message ceiling exceeded
}

# Fully-decayed score entries below this are pruned; offense/rate-limit
# books are capped so an attacker minting fresh identities (ed25519 keys
# are free) cannot grow the anti-DoS layer's own memory without bound.
SCORE_PRUNE_EPSILON = 0.01
MAX_TRACKED = 4096

SANCTION_NONE = "none"
SANCTION_DISCONNECT = "disconnect"
SANCTION_BAN = "ban"


@dataclass
class ScoreConfig:
    """Thresholds + decay for one node's scoreboard (config/config.py
    P2PConfig peer_* fields)."""

    halflife_s: float = 120.0         # score decay half-life
    disconnect_score: float = 50.0    # crossing => disconnect sanction
    ban_score: float = 100.0          # crossing => timed ban
    ban_duration_s: float = 30.0      # first ban
    ban_max_duration_s: float = 600.0  # cap for the re-offense backoff

    @staticmethod
    def from_p2p_config(p2p) -> "ScoreConfig":
        return ScoreConfig(
            halflife_s=p2p.peer_score_halflife_s,
            disconnect_score=p2p.peer_disconnect_score,
            ban_score=p2p.peer_ban_score,
            ban_duration_s=p2p.peer_ban_duration_s,
            ban_max_duration_s=p2p.peer_ban_max_duration_s,
        )


class PeerScoreBoard:
    """Per-peer decaying misbehavior scores with escalating sanctions.

    Thread-safe; one instance per Switch (in-process mesh nodes must not
    share one — each node sanctions independently). ``clock`` is
    injectable so ban-lifecycle tests drive simulated time.
    """

    def __init__(self, config: ScoreConfig | None = None,
                 clock=time.monotonic, logger=None):
        self.config = config if config is not None else ScoreConfig()
        self._clock = clock
        self.logger = logger
        self._mtx = threading.Lock()
        self._scores: dict[str, tuple[float, float]] = {}  # id -> (score, t)
        self._bans: dict[str, float] = {}                  # id -> ban_until
        self._ban_counts: dict[str, int] = {}              # lifetime re-offenses
        self._offenses: dict[tuple[str, str], int] = {}    # (id, offense) -> n
        self.bans_total = 0
        self.shed: dict[str, int] = {}                    # channel -> shed msgs
        self.rate_limited: dict[tuple[str, str], int] = {}  # (id, ch) -> n
        # sanction listeners, called OUTSIDE the lock:
        self.on_ban: list = []         # callbacks(peer_id, until_s)
        self.on_disconnect: list = []  # callbacks(peer_id, reason)

    # --- scoring -----------------------------------------------------------

    def _decayed_locked(self, peer_id: str, now: float) -> float:
        entry = self._scores.get(peer_id)
        if entry is None:
            return 0.0
        score, last = entry
        hl = self.config.halflife_s
        if hl > 0 and now > last:
            score *= 0.5 ** ((now - last) / hl)
        return score

    def record(self, peer_id: str, offense: str,
               points: float | None = None) -> str:
        """Score one offense; returns the sanction applied (``none``,
        ``disconnect``, or ``ban``). Unattributed reports (empty peer id)
        are dropped — a message we cannot attribute must not sanction
        anyone. Sanction callbacks fire outside the board lock."""
        if not peer_id:
            return SANCTION_NONE
        pts = points if points is not None else OFFENSE_POINTS.get(offense, 1.0)
        now = self._clock()
        sanction = SANCTION_NONE
        until = 0.0
        with self._mtx:
            key = (peer_id, offense)
            if key not in self._offenses and len(self._offenses) >= MAX_TRACKED:
                self._offenses.pop(next(iter(self._offenses)))
            self._offenses[key] = self._offenses.get(key, 0) + 1
            prev = self._decayed_locked(peer_id, now)
            score = prev + pts
            cfg = self.config
            if cfg.ban_score > 0 and score >= cfg.ban_score:
                until = self._install_ban_locked(peer_id, now, None)
                self._scores.pop(peer_id, None)
                sanction = SANCTION_BAN
            else:
                if (peer_id not in self._scores
                        and len(self._scores) >= MAX_TRACKED):
                    self._prune_scores_locked(now)
                self._scores[peer_id] = (score, now)
                if cfg.disconnect_score > 0 and score >= cfg.disconnect_score:
                    # EVERY offense at/above the threshold disconnects: a
                    # redialing peer pacing its score inside
                    # [disconnect, ban) must not misbehave sanction-free
                    sanction = SANCTION_DISCONNECT
        if sanction == SANCTION_BAN:
            if self.logger is not None:
                self.logger.info("peer banned", peer=peer_id[:12],
                                 offense=offense, until=until)
            for cb in list(self.on_ban):
                try:
                    cb(peer_id, until)
                except Exception:  # noqa: BLE001 - a listener must not block
                    pass
        elif sanction == SANCTION_DISCONNECT:
            for cb in list(self.on_disconnect):
                try:
                    cb(peer_id, f"misbehavior score threshold ({offense})")
                except Exception:  # noqa: BLE001
                    pass
        return sanction

    def _install_ban_locked(self, peer_id: str, now: float,
                            duration_s: float | None) -> float:
        """One escalation schedule for scored AND manual bans: first ban
        lasts ban_duration_s, doubling per prior offense up to the cap.
        The ban books are bounded too — an identity-minting attacker
        earning throwaway bans must not grow them forever (expired
        entries evict first; the re-offense history of the evicted
        oldest identities is the price of boundedness)."""
        n = self._ban_counts.get(peer_id, 0)
        dur = duration_s if duration_s is not None else min(
            self.config.ban_duration_s * (2.0 ** min(n, 16)),
            self.config.ban_max_duration_s)
        if peer_id not in self._bans and len(self._bans) >= MAX_TRACKED:
            # evict expired entries first; with none expired, evict the
            # most recently INSTALLED ban — under identity-minting
            # pressure (the only way the book fills) that is the
            # attacker's own previous throwaway identity, so minting can
            # never lift an older genuine offender's live ban early
            expired = [p for p, t in self._bans.items() if t <= now]
            victim = expired[0] if expired else next(reversed(self._bans))
            del self._bans[victim]
        if (peer_id not in self._ban_counts
                and len(self._ban_counts) >= MAX_TRACKED):
            self._ban_counts.pop(next(iter(self._ban_counts)))
        until = now + dur
        self._bans[peer_id] = until
        self._ban_counts[peer_id] = n + 1
        self.bans_total += 1
        return until

    def _prune_scores_locked(self, now: float) -> None:
        """Drop fully-decayed entries (and, under identity-minting
        pressure, the lowest scores past the cap): the anti-DoS layer
        must not itself grow without bound."""
        for pid in [p for p in self._scores
                    if self._decayed_locked(p, now) < SCORE_PRUNE_EPSILON]:
            del self._scores[pid]
        while len(self._scores) >= MAX_TRACKED:
            lowest = min(self._scores,
                         key=lambda p: self._decayed_locked(p, now))
            del self._scores[lowest]

    def score(self, peer_id: str) -> float:
        with self._mtx:
            return self._decayed_locked(peer_id, self._clock())

    # --- bans --------------------------------------------------------------

    def is_banned(self, peer_id: str) -> bool:
        """True while a ban is in force; expired bans are removed lazily
        (the re-offense count stays, so the NEXT ban backs off)."""
        if not peer_id:
            return False
        now = self._clock()
        with self._mtx:
            until = self._bans.get(peer_id)
            if until is None:
                return False
            if now >= until:
                del self._bans[peer_id]
                return False
            return True

    def ban(self, peer_id: str, duration_s: float | None = None) -> float:
        """Manually ban (operator action / tests); returns ban_until."""
        now = self._clock()
        with self._mtx:
            until = self._install_ban_locked(peer_id, now, duration_s)
        for cb in list(self.on_ban):
            try:
                cb(peer_id, until)
            except Exception:  # noqa: BLE001
                pass
        return until

    def unban(self, peer_id: str) -> None:
        with self._mtx:
            self._bans.pop(peer_id, None)

    # --- overload counters (fed by shed queues / rate limiters) ------------

    def count_shed(self, channel: str, n: int = 1) -> None:
        with self._mtx:
            self.shed[channel] = self.shed.get(channel, 0) + n

    def count_rate_limited(self, peer_id: str, channel: str) -> None:
        with self._mtx:
            key = (peer_id, channel)
            if key not in self.rate_limited and len(self.rate_limited) >= MAX_TRACKED:
                self.rate_limited.pop(next(iter(self.rate_limited)))
            self.rate_limited[key] = self.rate_limited.get(key, 0) + 1

    # --- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        """Monotonic counters + live gauges for the node metrics sampler
        (pumped as deltas, like the faults/nemesis planes)."""
        now = self._clock()
        with self._mtx:
            self._prune_scores_locked(now)
            return {
                "scores": {p: self._decayed_locked(p, now)
                           for p in self._scores},
                "bans_total": self.bans_total,
                "shed": dict(self.shed),
                "rate_limited": dict(self.rate_limited),
            }

    def describe(self) -> dict:
        """JSON-friendly state for the unsafe_peers RPC."""
        now = self._clock()
        with self._mtx:
            return {
                "scores": {p: round(self._decayed_locked(p, now), 3)
                           for p in self._scores},
                "banned": {p: round(until - now, 3)
                           for p, until in self._bans.items() if until > now},
                "ban_counts": dict(self._ban_counts),
                "bans_total": self.bans_total,
                "offenses": {f"{p}:{o}": n
                             for (p, o), n in self._offenses.items()},
                "shed": dict(self.shed),
                "rate_limited": {f"{p}:{ch}": n
                                 for (p, ch), n in self.rate_limited.items()},
                "config": {
                    "halflife_s": self.config.halflife_s,
                    "disconnect_score": self.config.disconnect_score,
                    "ban_score": self.config.ban_score,
                    "ban_duration_s": self.config.ban_duration_s,
                    "ban_max_duration_s": self.config.ban_max_duration_s,
                },
            }


# ---------------------------------------------------------------------------
# Per-channel inbound message ceilings
# ---------------------------------------------------------------------------


def parse_rate_spec(spec: str) -> dict[int, float]:
    """``"0x22:500,0x30:1000"`` -> {0x22: 500.0, 0x30: 1000.0} (channel id
    in any int base, msgs/s; rate <= 0 rejected — an accidental zero would
    silently blackhole a channel)."""
    out: dict[int, float] = {}
    for stmt in spec.split(","):
        stmt = stmt.strip()
        if not stmt:
            continue
        ch, sep, rate = stmt.partition(":")
        if not sep:
            raise ValueError(f"bad rate spec {stmt!r} (want ch:msgs_per_s)")
        r = float(rate)
        if r <= 0:
            raise ValueError(f"bad rate spec {stmt!r} (rate must be > 0)")
        out[int(ch, 0)] = r
    return out


class ChannelRateLimiter:
    """Token bucket per channel id: ``rate`` msgs/s sustained with a
    one-second burst. Channels with no configured rate are unlimited.
    One instance per MConnection, so the ceilings are per-peer."""

    def __init__(self, rates: dict[int, float], clock=time.monotonic):
        self._clock = clock
        self._mtx = threading.Lock()
        # ch -> [rate, burst_cap, tokens, last_refill]; the cap is at
        # least one whole message so fractional rates (e.g. 0.5 chunks/s)
        # accumulate to a deliverable token instead of silently
        # blackholing the channel forever
        self._buckets = {ch: [float(r), max(float(r), 1.0),
                              max(float(r), 1.0), clock()]
                         for ch, r in rates.items() if r > 0}

    def allow(self, ch_id: int) -> bool:
        b = self._buckets.get(ch_id)
        if b is None:
            return True
        now = self._clock()
        with self._mtx:
            rate, cap, tokens, last = b
            tokens = min(cap, tokens + rate * max(0.0, now - last))
            if tokens >= 1.0:
                b[2] = tokens - 1.0
                b[3] = now
                return True
            b[2] = tokens
            b[3] = now
            return False


# ---------------------------------------------------------------------------
# Priority load shedding
# ---------------------------------------------------------------------------

# Gossip message classes, lowest shed-priority first. Control items
# (priority=None) bypass shedding entirely: stop sentinels and sync
# barriers must never be lost to an overload.
PRIO_STALE = 0   # gossip for heights already committed (re-derivable)
PRIO_FUTURE = 1  # gossip ahead of the live height
PRIO_LIVE = 2    # votes/proposal/parts for the height being decided


class ShedQueue:
    """Bounded FIFO that sheds by priority instead of blocking producers
    (the SEDA admission discipline): when full, the oldest entry of the
    lowest priority class is evicted to admit a higher-priority arrival;
    an arrival no more important than everything queued is shed itself.
    FIFO order is preserved for everything admitted, so consumers see
    exactly the arrival-order semantics of queue.Queue minus dropped
    gossip — indistinguishable from p2p message loss, which gossip
    re-delivery already tolerates.

    API-compatible with the queue.Queue surface the consensus receive
    routine uses (put/get/get_nowait/empty, queue.Empty raised).
    """

    def __init__(self, maxsize: int = 0, on_shed=None):
        self.maxsize = maxsize
        self._dq: deque = deque()  # (priority | None, channel, item)
        self._mtx = threading.Lock()
        self._not_empty = threading.Condition(self._mtx)
        # per-priority population: put() decides evict-vs-shed in O(1)
        # in the common full-of-equal-priority flood case; the O(n)
        # victim scan runs only when an eviction will actually succeed
        self._prio_counts: dict[int, int] = {}
        self.shed_counts: dict[str, int] = {}
        self._on_shed = on_shed  # callback(channel) after the lock drops

    def put(self, item, priority: int | None = None,
            channel: str = "ctrl", block: bool = True,
            timeout=None) -> bool:
        """Admit ``item``; returns False when it was shed. ``priority``
        None marks a control item that is always admitted (the queue may
        exceed maxsize by the handful of in-flight sentinels). Never
        blocks regardless of ``block`` — that is the point."""
        shed_channel = None
        admitted = True
        with self._mtx:
            if (priority is not None and self.maxsize > 0
                    and len(self._dq) >= self.maxsize):
                if not any(n > 0 for p, n in self._prio_counts.items()
                           if p < priority):
                    # nothing strictly lower queued: shed the arrival
                    # (O(1) — the common case when a flood has filled the
                    # queue with its own priority class)
                    shed_channel = channel
                    admitted = False
                else:
                    # evict the oldest entry of the lowest class present
                    victim_i = None
                    victim_prio = priority
                    for i, (p, _ch, _it) in enumerate(self._dq):
                        if p is not None and p < victim_prio:
                            victim_i = i
                            victim_prio = p
                            if p == PRIO_STALE:
                                break  # nothing sheds earlier than stale
                    vp, shed_channel, _vi = self._dq[victim_i]
                    del self._dq[victim_i]
                    self._prio_counts[vp] -= 1
                self.shed_counts[shed_channel] = \
                    self.shed_counts.get(shed_channel, 0) + 1
            if admitted:
                self._dq.append((priority, channel, item))
                if priority is not None:
                    self._prio_counts[priority] = \
                        self._prio_counts.get(priority, 0) + 1
                self._not_empty.notify()
        if shed_channel is not None and self._on_shed is not None:
            try:
                self._on_shed(shed_channel)
            except Exception:  # noqa: BLE001 - metrics must not break the path
                pass
        return admitted

    def get(self, block: bool = True, timeout=None):
        with self._not_empty:
            if not block:
                if not self._dq:
                    raise _queue.Empty
            elif timeout is None:
                while not self._dq:
                    self._not_empty.wait()
            else:
                deadline = time.monotonic() + timeout
                while not self._dq:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise _queue.Empty
                    self._not_empty.wait(remaining)
            prio, _ch, item = self._dq.popleft()
            if prio is not None:
                self._prio_counts[prio] -= 1
            return item

    def get_nowait(self):
        return self.get(block=False)

    def empty(self) -> bool:
        with self._mtx:
            return not self._dq

    def qsize(self) -> int:
        with self._mtx:
            return len(self._dq)
