"""Flow-rate monitoring + throttling (reference: libs/flowrate/flowrate.go,
the mxk/go-flowrate vendored by the reference for MConnection send/recv
accounting and rate limiting).

Monitor tracks transfer progress with an exponentially-weighted moving rate;
Limit() tells a caller how many bytes it may move now to stay under a target
rate, sleeping like the reference's blocking mode when nothing is allowed.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass


@dataclass
class Status:
    """reference: flowrate.go Status."""

    bytes_total: int
    duration_s: float
    cur_rate: float  # EWMA bytes/sec
    avg_rate: float
    peak_rate: float


class Monitor:
    """reference: flowrate.go Monitor (sample period 100ms, EWMA)."""

    def __init__(self, sample_period_s: float = 0.1, ewma_window_s: float = 1.0):
        self._period = sample_period_s
        self._alpha = sample_period_s / ewma_window_s
        self._mtx = threading.Lock()
        self._start = time.monotonic()
        self._total = 0
        self._acc = 0  # bytes in the current sample window
        self._last_sample = self._start
        self._rate = 0.0
        self._peak = 0.0
        # token bucket backing limit(): armed on first limit() call;
        # update() then debits it (see limit() docstring)
        self._budget: float | None = None
        self._budget_t = self._start

    def update(self, n: int) -> int:
        """Record n transferred bytes (reference Update)."""
        with self._mtx:
            self._acc += n
            self._total += n
            if self._budget is not None:
                # debit the limiter's token bucket; going negative (the
                # caller moved more than granted, e.g. a full socket
                # buffer) just forces a longer refill sleep
                self._budget -= n
            self._sample_locked()
        return n

    def _sample_locked(self) -> None:
        now = time.monotonic()
        elapsed = now - self._last_sample
        if elapsed < self._period:
            return
        inst = self._acc / elapsed
        # catch up the EWMA over however many periods elapsed
        k = min(int(elapsed / self._period), 20)
        for _ in range(k):
            self._rate += self._alpha * (inst - self._rate)
        self._peak = max(self._peak, self._rate)
        self._acc = 0
        self._last_sample = now

    def status(self) -> Status:
        with self._mtx:
            self._sample_locked()
            dur = time.monotonic() - self._start
            return Status(
                bytes_total=self._total,
                duration_s=dur,
                cur_rate=self._rate,
                avg_rate=self._total / dur if dur > 0 else 0.0,
                peak_rate=self._peak,
            )

    def limit(self, want: int, rate: int, block: bool = True) -> int:
        """How many of `want` bytes may move now to hold `rate` B/s
        (reference Limit). rate <= 0 means unlimited. In blocking mode,
        sleeps until at least one byte is allowed.

        Implemented as a token bucket refilled at `rate` and capped at
        ONE second of burst credit (update() debits it). A cumulative
        since-start budget would let a peer that idles for an hour bank
        3600×rate of unspent allowance and then flood unthrottled for
        gigabytes — the exact attack the recv-side limiter exists to
        stop (docs/OVERLOAD.md)."""
        if rate <= 0 or want <= 0:
            return want
        while True:
            with self._mtx:
                now = time.monotonic()
                if self._budget is None:
                    self._budget = rate * self._period  # small head start
                else:
                    self._budget = min(
                        self._budget + rate * (now - self._budget_t),
                        float(rate))  # burst cap: 1s of credit
                self._budget_t = now
                allowed = int(self._budget)
            if allowed >= 1 or not block:
                return max(0, min(want, allowed))
            # sleep just long enough for one sample period of budget
            time.sleep(self._period)
