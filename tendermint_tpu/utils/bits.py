"""BitArray: the vote/part bitmap type (reference: libs/bits/bit_array.go,
proto/tendermint/libs/bits/types.proto).

Backed by a single Python int (arbitrary-precision bitmask), which makes
or/and/sub/is_full O(words) and keeps indexing trivial. Drop-in for the
list[bool] bitmaps it replaces: supports len/index/slice/iter/assignment.
"""

from __future__ import annotations

import random

from tendermint_tpu.encoding import proto


class BitArray:
    __slots__ = ("bits", "_mask")

    def __init__(self, bits: int = 0):
        if bits < 0:
            raise ValueError("negative bit count")
        self.bits = bits
        self._mask = 0

    # --- constructors -------------------------------------------------------

    @staticmethod
    def from_bools(bools) -> "BitArray":
        ba = BitArray(len(bools))
        m = 0
        for i, b in enumerate(bools):
            if b:
                m |= 1 << i
        ba._mask = m
        return ba

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._mask = self._mask
        return ba

    # --- element access (list[bool] compatible) -----------------------------

    def __len__(self) -> int:
        return self.bits

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [bool(self._mask >> k & 1) for k in range(*i.indices(self.bits))]
        if i < 0:
            i += self.bits
        if not 0 <= i < self.bits:
            raise IndexError(i)
        return bool(self._mask >> i & 1)

    def __setitem__(self, i: int, v: bool) -> None:
        if i < 0:
            i += self.bits
        if not 0 <= i < self.bits:
            raise IndexError(i)
        if v:
            self._mask |= 1 << i
        else:
            self._mask &= ~(1 << i)

    def __iter__(self):
        m = self._mask
        for _ in range(self.bits):
            yield bool(m & 1)
            m >>= 1

    def get_index(self, i: int) -> bool:
        return bool(self[i]) if 0 <= i < self.bits else False

    def set_index(self, i: int, v: bool) -> bool:
        if not 0 <= i < self.bits:
            return False
        self[i] = v
        return True

    # --- set ops (reference: bit_array.go Or/And/Sub/Not) -------------------

    def or_(self, other: "BitArray") -> "BitArray":
        ba = BitArray(max(self.bits, other.bits))
        ba._mask = self._mask | other._mask
        return ba

    def and_(self, other: "BitArray") -> "BitArray":
        ba = BitArray(min(self.bits, other.bits))
        ba._mask = self._mask & other._mask & ((1 << ba.bits) - 1)
        return ba

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (reference Sub truncates to
        self's length)."""
        ba = BitArray(self.bits)
        ba._mask = self._mask & ~other._mask & ((1 << self.bits) - 1)
        return ba

    def not_(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._mask = ~self._mask & ((1 << self.bits) - 1)
        return ba

    def update(self, other: "BitArray") -> None:
        """In-place or with another array (reference Update)."""
        self._mask |= other._mask & ((1 << self.bits) - 1)

    # --- queries ------------------------------------------------------------

    def is_empty(self) -> bool:
        return self._mask == 0

    def is_full(self) -> bool:
        return self.bits > 0 and self._mask == (1 << self.bits) - 1

    def sum(self) -> int:
        return bin(self._mask).count("1")

    def pick_random(self, rng: random.Random | None = None) -> tuple[int, bool]:
        """A uniformly random set index (reference PickRandom)."""
        set_bits = [i for i in range(self.bits) if self._mask >> i & 1]
        if not set_bits:
            return 0, False
        return (rng or random).choice(set_bits), True

    # --- wire (proto/tendermint/libs/bits/types.proto) ----------------------

    def marshal(self) -> bytes:
        """bits=1 varint, elems=2 packed uint64 (proto/tendermint/libs/bits)."""
        elems = [(self._mask >> i) & 0xFFFFFFFFFFFFFFFF
                 for i in range(0, self.bits, 64)]
        return proto.Writer().varint(1, self.bits).packed_varints(2, elems).out()

    @staticmethod
    def unmarshal(buf: bytes) -> "BitArray":
        f = proto.fields(buf)
        ba = BitArray(proto.as_sint64(f.get(1, [0])[-1]))
        elems = []
        for raw in f.get(2, []):
            if isinstance(raw, bytes):  # packed
                pos = 0
                while pos < len(raw):
                    v, pos = proto.decode_uvarint(raw, pos)
                    elems.append(v)
            else:
                elems.append(raw)
        m = 0
        for i, elem in enumerate(elems):
            m |= elem << (64 * i)
        ba._mask = m & ((1 << ba.bits) - 1) if ba.bits else 0
        return ba

    # --- display (reference String: "x" = set, "_" = unset) -----------------

    def __str__(self) -> str:
        return "".join("x" if b else "_" for b in self)

    def __repr__(self) -> str:
        return f"BitArray{{{self}}}"

    def __eq__(self, other) -> bool:
        if isinstance(other, BitArray):
            return self.bits == other.bits and self._mask == other._mask
        if isinstance(other, (list, tuple)):
            return list(self) == list(other)
        return NotImplemented
