"""Structured key-value logger (reference: libs/log — go-kit style).

Every subsystem takes a `logger=` parameter; this is the implementation
behind it. Supports plain ("terminal") and JSON formats, level filtering,
and contextual binding via `with_(module=...)` exactly like the reference's
`logger.With("module", "consensus")`.
"""

from __future__ import annotations

import json
import sys
import threading
import time

DEBUG, INFO, ERROR = 10, 20, 40
_LEVELS = {"debug": DEBUG, "info": INFO, "error": ERROR}
_NAMES = {DEBUG: "DBG", INFO: "INF", ERROR: "ERR"}


class Logger:
    """reference: libs/log/logger.go Logger interface."""

    def __init__(self, sink=None, level: str = "info", fmt: str = "plain",
                 _bound: dict | None = None, _lock=None):
        self._sink = sink if sink is not None else sys.stderr
        self._level = _LEVELS.get(level, INFO)
        self._fmt = fmt
        self._bound = dict(_bound or {})
        self._lock = _lock if _lock is not None else threading.Lock()

    def with_(self, **kv) -> "Logger":
        merged = {**self._bound, **kv}
        lg = Logger(self._sink, fmt=self._fmt, _bound=merged, _lock=self._lock)
        lg._level = self._level
        return lg

    def debug(self, msg: str, **kv) -> None:
        self._log(DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._log(INFO, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._log(ERROR, msg, kv)

    def _log(self, level: int, msg: str, kv: dict) -> None:
        if level < self._level:
            return
        record = {**self._bound, **kv}
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
        if self._fmt == "json":
            doc = {"ts": ts, "level": _NAMES[level], "msg": msg}
            doc.update({k: _scrub(v) for k, v in record.items()})
            line = json.dumps(doc, default=str)
        else:
            pairs = " ".join(f"{k}={_scrub(v)}" for k, v in record.items())
            line = f"{_NAMES[level]}[{ts}] {msg}" + (f" {pairs}" if pairs else "")
        with self._lock:
            print(line, file=self._sink)


def _scrub(v):
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, Exception):
        return f"{type(v).__name__}: {v}"
    return v


class NopLogger:
    """reference: libs/log/nop_logger.go."""

    def with_(self, **kv) -> "NopLogger":
        return self

    def debug(self, msg: str, **kv) -> None:
        pass

    def info(self, msg: str, **kv) -> None:
        pass

    def error(self, msg: str, **kv) -> None:
        pass


def new_logger(level: str = "info", fmt: str = "plain", sink=None) -> Logger:
    return Logger(sink=sink, level=level, fmt=fmt)
