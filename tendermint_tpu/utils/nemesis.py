"""Nemesis: the peer-scoped link fault plane (reference: the perturbation
dimension of test/e2e/ — runner/perturb.go kills processes; this layer cuts
LINKS, which no process signal can express).

Where utils/faults.py rules fire *globally* per site, nemesis rules are
keyed by the (local node id, remote peer id) of one directed link. The p2p
choke points — ``p2p.send``, ``p2p.recv``, ``p2p.dial`` — pass that context
through :func:`tendermint_tpu.utils.faults.link_outcome`, which consults
the global site rules first (unchanged semantics) and then this plane. One
process can therefore host many in-process nodes and still cut exactly the
links between them, and a real testnet can partition itself symmetrically
by installing the same groups on every node.

Two fault shapes:

* **Partition** — ``partition(groups)`` installs disjoint groups of
  node-id prefixes; links between nodes of *different* groups are SEVERED
  (the first crossing message tears the connection down like a transport
  error, and dials are refused), exactly what a firewall cut does to TCP.
  Nodes in no group are unaffected, so partial specs compose. ``heal()``
  removes the partition and notifies ``on_heal`` listeners (the p2p
  switch uses this to forget reconnect backoff so healed persistent links
  redial immediately and rebuild peer gossip state from scratch).
* **Link rules** — directed ``src>dst`` rules with the faults-style action
  set: ``drop``, ``delay`` (with seeded jitter), ``dup`` (deliver twice),
  ``disconnect`` (tear the connection down like a transport error), and
  ``flood`` (send-side byzantine amplification: every outbound message
  crossing the link additionally enqueues ``~param`` seeded CORRUPTED
  copies — invalid-signature votes, unparseable/oversized gossip — the
  overload-resilience scenario driver, docs/OVERLOAD.md). A rule
  on one direction only is an asymmetric link; ``%prob`` makes it flap.

Determinism composes with the faults layer: every probabilistic decision
for hit *k* of a directed link is a pure function of
``(TMTPU_FAULT_SEED, site, local, remote, k)`` — per-link hit counters make
schedules independent of thread interleavings across links, exactly like
the per-site counters of faults.py.

Environment grammar (``TMTPU_NEMESIS``; comma-separated statements):

    partition=<group>|<group>[|...]      group = id-prefix[/id-prefix...]
    link=<src>><dst>:<action>[~<param>][%<prob>][#<channel>]
    heal@<seconds>                       auto-heal partitions after t s

    TMTPU_NEMESIS="partition=ab12/cd34|ef56,heal@5"
    TMTPU_NEMESIS="link=*>ab12:drop%0.3,link=ab12>*:delay~0.05"
    TMTPU_NEMESIS="link=*>ab12:drop#0x22"   # starve only the vote channel

``src``/``dst`` are node-id prefixes or ``*``. ``delay~p`` sleeps a seeded
uniform in [p/2, p] (per-link jitter); ``dup`` re-delivers the message
once; ``disconnect`` raises :class:`faults.FaultDisconnect` into the
connection error path; ``#ch`` scopes a rule to one mconnection channel
(a vote-starved-but-reachable peer is a different failure than a dead
link). The seed is ``TMTPU_FAULT_SEED`` — one seed replays the whole
chaos schedule, faults and nemesis together.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field

from tendermint_tpu.utils import faults

LINK_SITES = ("p2p.send", "p2p.recv", "p2p.dial")
_LINK_ACTIONS = {"drop", "delay", "dup", "disconnect", "flood"}
FLOOD_DEFAULT_COPIES = 8
FLOOD_PAD_BYTES = 33  # corrupted copies grow by this (oversized-tx knob)


def _match(pattern: str, node_id: str) -> bool:
    """Node-id prefix match; '*' matches anything, including an unknown
    (empty) id. A non-wildcard pattern never matches an unknown id — a
    link we cannot attribute must not burn a scoped rule's trigger."""
    if pattern == "*":
        return True
    return bool(pattern) and bool(node_id) and node_id.startswith(pattern)


@dataclass
class LinkRule:
    """One directed link rule: ``src>dst:action[~param][%prob][#ch]``."""

    src: str
    dst: str
    action: str
    param: float | None = None
    prob: float | None = None
    ch: int | None = None  # scope to one mconnection channel id
    fired: int = field(default=0, compare=False)

    @staticmethod
    def parse(spec: str) -> "LinkRule":
        """``src>dst:action[~param][%prob][#ch]`` -> LinkRule."""
        link, sep, rest = spec.strip().partition(":")
        src, sep2, dst = link.partition(">")
        action, param, prob, ch = rest, None, None, None
        if "#" in action:
            action, _, c = action.partition("#")
            ch = int(c, 0)  # accepts 0x22 and 34 alike
        if "%" in action:
            action, _, p = action.partition("%")
            prob = float(p)
        if "~" in action:
            action, _, p = action.partition("~")
            param = float(p)
        if (not sep or not sep2 or not src or not dst
                or action not in _LINK_ACTIONS):
            raise ValueError(f"bad link spec {spec!r} "
                             "(want src>dst:action[~p][%prob][#ch])")
        return LinkRule(src=src, dst=dst, action=action, param=param,
                        prob=prob, ch=ch)


class NemesisPlane:
    """Partition groups + directed link rules, consulted by the p2p fault
    sites with (local, remote) context. ``active`` is a plain attribute so
    the no-nemesis hot path costs one attribute read."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._groups: list[tuple[str, ...]] = []
        self._rules: list[LinkRule] = []
        self._hits: dict[tuple[str, str, str], int] = {}
        self._fired: dict[tuple[str, str], int] = {}
        self._heal_timer: threading.Timer | None = None
        self.active = False
        self.on_heal: list = []  # callbacks() after a heal()

    # --- configuration -----------------------------------------------------

    def _seed(self) -> int:
        return faults.REGISTRY.seed

    def partition(self, groups) -> None:
        """Install a partition: ``groups`` is an iterable of groups, each an
        iterable of node-id prefixes. Links between different groups are
        severed (connections torn down, dials refused) until :meth:`heal`."""
        gs = [tuple(str(g) for g in group) for group in groups if group]
        with self._lock:
            self._groups = gs
            self.active = bool(self._groups or self._rules)

    def heal(self) -> None:
        """Remove the partition (link rules stay) and notify listeners."""
        with self._lock:
            self._groups = []
            self.active = bool(self._rules)
            if self._heal_timer is not None:
                self._heal_timer.cancel()
                self._heal_timer = None
            listeners = list(self.on_heal)
        for cb in listeners:
            try:
                cb()
            except Exception:  # noqa: BLE001 - a listener must not block heal
                pass

    def add_link(self, spec_or_rule) -> LinkRule:
        """Add one directed link rule (spec string or LinkRule)."""
        r = (spec_or_rule if isinstance(spec_or_rule, LinkRule)
             else LinkRule.parse(spec_or_rule))
        with self._lock:
            self._rules.append(r)
            self.active = True
        return r

    def remove_link(self, rule: LinkRule) -> None:
        """Remove exactly one previously-added rule (the object returned by
        :meth:`add_link`). Scheduled-fault drivers (e2e/soak.py) expire
        their own rules this way — a global ``clear()`` would also wipe
        OTHER schedules' still-active rules and an installed partition."""
        with self._lock:
            try:
                self._rules.remove(rule)
            except ValueError:
                pass
            self.active = bool(self._groups or self._rules)

    def clear(self) -> None:
        """Drop everything: partition, link rules, hit counters."""
        with self._lock:
            self._groups = []
            self._rules = []
            self._hits = {}
            self._fired = {}
            self.active = False
            if self._heal_timer is not None:
                self._heal_timer.cancel()
                self._heal_timer = None

    def reset_counters(self) -> None:
        """Zero hit/fired counters (same rules): seeded replay."""
        with self._lock:
            self._hits = {}
            self._fired = {}
            for r in self._rules:
                r.fired = 0

    def configure(self, spec: str) -> None:
        """Replace the whole plane from a TMTPU_NEMESIS grammar string."""
        groups: list[tuple[str, ...]] = []
        rules: list[LinkRule] = []
        heal_after: float | None = None
        for stmt in spec.split(","):
            stmt = stmt.strip()
            if not stmt:
                continue
            if stmt.startswith("partition="):
                groups = [tuple(p for p in g.split("/") if p)
                          for g in stmt[len("partition="):].split("|") if g]
            elif stmt.startswith("link="):
                rules.append(LinkRule.parse(stmt[len("link="):]))
            elif stmt.startswith("heal@"):
                heal_after = float(stmt[len("heal@"):])
            else:
                raise ValueError(f"bad nemesis statement {stmt!r} "
                                 "(want partition=…|…, link=…, or heal@t)")
        with self._lock:
            self._groups = groups
            self._rules = rules
            self._hits = {}
            self._fired = {}
            self.active = bool(groups or rules)
            if self._heal_timer is not None:
                self._heal_timer.cancel()
                self._heal_timer = None
            if heal_after is not None and groups:
                self._heal_timer = threading.Timer(heal_after, self.heal)
                self._heal_timer.daemon = True
                self._heal_timer.start()

    def install_from_env(self) -> None:
        """(Re)load TMTPU_NEMESIS. Like faults.install_from_env, an empty
        env leaves a programmatically-installed plane untouched."""
        spec = os.environ.get("TMTPU_NEMESIS", "")
        if not spec.strip():
            return
        self.configure(spec)

    # --- the decision point ------------------------------------------------

    def _cut(self, a: str, b: str) -> bool:
        """True when the partition separates node ids a and b."""
        ga = gb = None
        for i, group in enumerate(self._groups):
            if ga is None and any(_match(p, a) for p in group):
                ga = i
            if gb is None and any(_match(p, b) for p in group):
                gb = i
        return ga is not None and gb is not None and ga != gb

    def outcome(self, site: str, local: str, remote: str,
                channel: int | None = None) -> str:
        """Verdict for one message/dial on the directed link. Returns
        ``'pass'``, ``'drop'``, or ``'dup'``; sleeps for delay rules;
        raises FaultDisconnect (disconnect rule) or FaultInjected (a dial
        across a partition). Direction is message-travel: ``p2p.send`` and
        ``p2p.dial`` travel local->remote, ``p2p.recv`` remote->local.
        ``channel`` is the mconnection channel id at the message sites
        (None at ``p2p.dial``); channel-scoped rules only see it."""
        if not self.active:
            return "pass"
        src, dst = (remote, local) if site == "p2p.recv" else (local, remote)
        delay: float | None = None
        verdict = "pass"
        with self._lock:
            key = (site, local[:16], remote[:16])
            idx = self._hits.get(key, 0) + 1
            self._hits[key] = idx
            # The per-hit rng is built LAZILY: seeding random.Random from a
            # string hashes it, and only probabilistic/jittered rules ever
            # draw — a pure partition must not pay that inside the one
            # plane-wide lock on every message. Laziness preserves the
            # determinism contract: the rng still depends only on
            # (seed, site, link, hit index), and the draw sequence within
            # a hit is fixed by the rule list.
            rng: random.Random | None = None

            def _rng() -> random.Random:
                nonlocal rng
                if rng is None:
                    rng = random.Random(f"{self._seed()}:nemesis:{site}:"
                                        f"{local[:16]}:{remote[:16]}:{idx}")
                return rng

            if self._groups and self._cut(local, remote):
                self._fired[(site, "cut")] = self._fired.get((site, "cut"), 0) + 1
                verdict = "cut"
            else:
                for r in self._rules:
                    if not (_match(r.src, src) and _match(r.dst, dst)):
                        continue
                    if r.ch is not None and r.ch != channel:
                        continue
                    if r.action == "flood" and site == "p2p.recv":
                        # flood is send-side only: the SENDER amplifies.
                        # Matching at recv too would re-amplify every
                        # corrupted copy in an in-process mesh (both ends
                        # consult the same plane).
                        continue
                    if r.prob is not None and _rng().random() >= r.prob:
                        continue
                    r.fired += 1
                    self._fired[(site, r.action)] = \
                        self._fired.get((site, r.action), 0) + 1
                    if r.action == "delay":
                        # seeded per-link jitter: uniform in [p/2, p]
                        p = r.param if r.param is not None else 0.05
                        delay = p * (0.5 + 0.5 * _rng().random())
                        continue  # delay composes with a later drop/dup rule
                    verdict = r.action
                    break
        if delay is not None:
            time.sleep(delay)
        if verdict == "cut":
            # A partition SEVERS the link (like the reference e2e's docker
            # network disconnect): the first crossing message tears the
            # connection down and redials are refused until heal. Silent
            # per-message drops would poison gossip bookkeeping — try_send
            # reports success, peers get marked as having votes they never
            # saw, and the net deadlocks at the height even after heal.
            # Teardown + reconnect rebuilds peer state from scratch.
            if site == "p2p.dial":
                raise faults.FaultInjected(site)
            raise faults.FaultDisconnect(site)
        if verdict == "disconnect":
            raise faults.FaultDisconnect(site)
        if verdict in ("dup", "flood") and site == "p2p.dial":
            # a duplicated/flooded dial makes no sense; a schedule that asks
            # for it is misconfigured -- fail loudly like faults._apply does
            raise faults.FaultError(
                f"action {verdict!r} is not supported at site {site!r}")
        return verdict

    def flood_payloads(self, local: str, remote: str,
                       channel: int | None, msg: bytes) -> list[bytes]:
        """Corrupted copies for a message whose send just drew the
        ``flood`` verdict: the byzantine amplification a flooding peer
        performs on its own traffic. Copy count is the matching rule's
        ``~param`` (default 8). Even copies get one seeded byte flipped in
        the tail — inside a Vote's signature/timestamp region, so they
        parse but fail signature verification and exercise the per-lane
        drain attribution; odd copies get :data:`FLOOD_PAD_BYTES` of
        seeded junk appended — unparseable/oversized at the receiver.
        Deterministic: the k-th flood of a directed link is a pure
        function of (TMTPU_FAULT_SEED, link, k)."""
        with self._lock:
            count = FLOOD_DEFAULT_COPIES
            for r in self._rules:
                if r.action != "flood":
                    continue
                if not (_match(r.src, local) and _match(r.dst, remote)):
                    continue
                if r.ch is not None and r.ch != channel:
                    continue
                count = int(r.param) if r.param is not None else count
                break
            key = ("flood", local[:16], remote[:16])
            k = self._hits.get(key, 0) + 1
            self._hits[key] = k
            seed = self._seed()
        rng = random.Random(f"{seed}:flood:{local[:16]}:{remote[:16]}:{k}")
        out: list[bytes] = []
        for i in range(max(count, 0)):
            if not msg:
                break
            if i % 2 == 0:
                buf = bytearray(msg)
                # flip a byte near the tail (signature territory in a Vote)
                pos = len(buf) - 1 - rng.randrange(min(24, len(buf)))
                buf[pos] ^= (rng.randrange(255) + 1) & 0xFF
                out.append(bytes(buf))
            else:
                out.append(msg + rng.randbytes(FLOOD_PAD_BYTES))
        return out

    # --- observability -----------------------------------------------------

    def snapshot(self) -> tuple[dict, dict]:
        """(per-link hit counts keyed (site, local, remote), fired counts
        keyed (site, action)) — consumed by the node metrics sampler."""
        with self._lock:
            return dict(self._hits), dict(self._fired)

    def describe(self) -> dict:
        """JSON-friendly state for the unsafe_nemesis RPC."""
        with self._lock:
            return {
                "active": self.active,
                "partition": [list(g) for g in self._groups],
                "links": [f"{r.src}>{r.dst}:{r.action}"
                          + (f"~{r.param}" if r.param is not None else "")
                          + (f"%{r.prob}" if r.prob is not None else "")
                          + (f"#{r.ch:#x}" if r.ch is not None else "")
                          for r in self._rules],
                "fired": {f"{site}:{action}": n
                          for (site, action), n in self._fired.items()},
            }


PLANE = NemesisPlane()


# Module-level helpers (mirror utils/faults.py's surface)

def partition(groups) -> None:
    PLANE.partition(groups)


def heal() -> None:
    PLANE.heal()


def add_link(spec_or_rule) -> LinkRule:
    return PLANE.add_link(spec_or_rule)


def remove_link(rule: LinkRule) -> None:
    PLANE.remove_link(rule)


def clear() -> None:
    PLANE.clear()


def configure(spec: str) -> None:
    PLANE.configure(spec)


def install_from_env() -> None:
    PLANE.install_from_env()


def outcome(site: str, local: str, remote: str,
            channel: int | None = None) -> str:
    return PLANE.outcome(site, local, remote, channel)


# Like faults, env config is live from import: child processes (e2e nodes)
# inherit TMTPU_NEMESIS with no wiring call.
PLANE.install_from_env()
