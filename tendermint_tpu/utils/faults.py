"""Deterministic fault injection: named sites, seeded triggers, replayable
schedules (supersedes utils/fail.py; reference: libs/fail/fail.go:10-38 and
the WAL crash-recovery discipline of consensus/replay_test.go).

A fault *site* is a named choke point the framework passes through on its
hot paths -- WAL appends and fsyncs, store writes, p2p send/recv/dial, ABCI
socket round trips, batch-verifier device dispatch, and the five
finalize-commit crash sites. Instrumented modules call ``fire(site)`` (or a
site-shaped helper like ``torn_write``/``maybe_drop``); with no rules
configured that is one attribute read, so production pays nothing.

A *rule* attaches an action to a site. Rules come from the environment
(``TMTPU_FAULTS``) or the in-process API (``configure``):

    TMTPU_FAULT_SEED=1234
    TMTPU_FAULTS="wal.write:torn@12,ops.ed25519.device:raise%0.5x2"

Rule grammar: ``site:action[~param][@nth|%prob][xtimes]``
  * ``@nth``  -- fire on exactly the Nth hit of the site (1-based). Fires
    once unless ``xtimes`` widens it (then on hits N, N+1, ... N+times-1).
  * ``%prob`` -- fire each hit with probability ``prob``; the decision for
    hit k of a site is a pure function of (seed, site, k), so a schedule is
    replayable from the seed alone regardless of thread interleavings
    across sites.
  * no trigger -- fire on every hit.
  * ``~param`` -- action parameter (delay seconds; torn/partial cut byte).

Actions:
  * ``crash``      -- hard process exit (``os._exit(1)``; ``crash_fn``
    replaceable so in-process tests can observe the "crash" as an
    exception).
  * ``raise``      -- raise :class:`FaultInjected` into the caller.
  * ``delay``      -- sleep ``param`` seconds (default 0.05).
  * ``torn``       -- (write sites) append a prefix of the frame cut inside
    the BODY, fsync, then crash: a torn frame on disk.
  * ``partial``    -- like ``torn`` but cut inside the length/crc header.
  * ``drop``       -- (message sites) silently discard the message; at a
    record-read site the row reads back as missing.
  * ``disconnect`` -- (p2p sites) raise :class:`FaultDisconnect`, which the
    connection error path turns into a peer teardown.
  * ``bitrot``     -- (record-read sites, ``store.*.load``) flip one
    deterministic bit in the value on its way out of the DB.
  * ``truncate``   -- (record-read sites) cut the value to a deterministic
    prefix — a torn at-rest record.

The legacy ``TMTPU_FAIL_INDEX`` global-counter contract of utils/fail.py is
preserved verbatim by :func:`fail_point` (the crash matrix in
tests/test_fastsync_recovery.py depends on its exact counting).
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from dataclasses import dataclass, field


class FaultError(Exception):
    pass


class FaultInjected(FaultError):
    """Raised into the instrumented component by a ``raise`` rule."""

    def __init__(self, site: str):
        self.site = site
        super().__init__(f"fault injected at site {site!r}")


class FaultDisconnect(FaultInjected):
    """A ``disconnect`` rule: the connection layer treats it as a fatal
    transport error (peer teardown + persistent-peer reconnect)."""


# The operator-facing site contract. fire()/check() auto-register unknown
# names, but everything the framework instruments is declared here so
# docs/FAULTS.md, the crash matrix, and sites() can never drift apart.
CANONICAL_SITES: dict[str, str] = {
    "wal.write": "WAL frame append (consensus/wal.py _write_locked); "
                 "torn/partial leave a cut frame on disk then crash",
    "wal.fsync": "before the fsync of WAL write_sync/flush_and_sync; "
                 "crash here loses buffered frames",
    "store.block.save": "before BlockStore.save_block's atomic batch write",
    "store.state.save": "before StateStore.save writes the state key "
                        "(after the validator/params history rows)",
    "store.block.load": "every BlockStore record read (meta/part/commit/"
                        "seen-commit/BH/state rows), pre-decode; bitrot/"
                        "truncate mutate the value in flight, drop loses it",
    "store.state.load": "every StateStore record read (state key, validator/"
                        "params history, ABCI responses), pre-decode",
    "store.evidence.load": "every evidence-pool record read (pending/"
                           "committed rows), pre-decode",
    "store.txindex.load": "every tx/block-indexer record read (documents "
                          "and event postings), pre-decode",
    "p2p.send": "outbound MConnection message (drop/delay/disconnect)",
    "p2p.recv": "inbound MConnection message, pre-delivery "
                "(drop/delay/disconnect)",
    "p2p.dial": "Transport.dial of an outbound peer (raise/delay)",
    "abci.call": "one ABCI socket round trip (raise/delay/crash)",
    "mempool.ingest": "one batched CheckTx dispatch of the ingestion front "
                      "door (mempool check_tx_batch + the batched recheck); "
                      "failures degrade to the serial per-tx CheckTx loop",
    "abci.deliver_batch": "one batched DeliverTx chunk dispatch of the "
                          "execution plane (state/execution.py "
                          "deliver_block_txs); fires BEFORE the dispatch, "
                          "so an injected failure degrades that chunk to "
                          "the serial per-tx DeliverTx loop without "
                          "double-applying any tx",
    "ops.ed25519.device": "ed25519 batch-verifier device dispatch; failures "
                          "trip the circuit breaker onto the host fallback",
    "ops.sr25519.device": "sr25519 batch-verifier device dispatch (twin "
                          "breaker)",
    "ops.ed25519.probe": "the breaker's background device re-probe; a "
                         "SEPARATE site so probe timing never consumes "
                         "consensus-path hit indices (replayability)",
    "ops.sr25519.probe": "sr25519 twin of ops.ed25519.probe",
    "consensus.finalize.save_block": "finalize-commit crash site 1 "
                                     "(reference state.go:1605)",
    "consensus.finalize.end_height": "crash site 2: before the WAL "
                                     "EndHeight fsync (state.go:1619)",
    "consensus.finalize.apply_block": "crash site 3: before apply_block "
                                      "(state.go:1642)",
    "consensus.finalize.prune": "crash site 4: before pruning "
                                "(state.go:1667)",
    "consensus.finalize.done": "crash site 5: after update_to_state "
                               "(state.go:1685)",
    "light.gateway.fetch": "one provider fetch attempt inside the light "
                           "gateway (light/gateway.py); raise/delay exercise "
                           "retry with backoff, hedged secondaries, and "
                           "provider-scoreboard demotion/failover",
}

_SPEC_RE = re.compile(
    r"^(?P<action>[a-z_]+)"
    r"(?:~(?P<param>[0-9.]+))?"
    r"(?:@(?P<nth>\d+)|%(?P<prob>[0-9.]+))?"
    r"(?:x(?P<times>\d+))?$"
)

_ACTIONS = {"crash", "raise", "delay", "torn", "partial", "drop", "disconnect",
            "bitrot", "truncate"}


@dataclass
class Rule:
    site: str
    action: str
    param: float | None = None
    nth: int | None = None       # 1-based hit index
    prob: float | None = None
    times: int | None = None     # max firings (None = unlimited for %/always)
    fired: int = field(default=0, compare=False)

    @staticmethod
    def parse(spec: str) -> "Rule":
        """``site:action[~param][@nth|%prob][xtimes]`` -> Rule."""
        site, sep, rest = spec.strip().partition(":")
        m = _SPEC_RE.match(rest) if sep else None
        if not site or m is None or m.group("action") not in _ACTIONS:
            raise ValueError(f"bad fault spec {spec!r} "
                             "(want site:action[~p][@n|%p][xk])")
        nth = int(m.group("nth")) if m.group("nth") else None
        times = int(m.group("times")) if m.group("times") else None
        if nth is not None and times is None:
            times = 1
        return Rule(
            site=site, action=m.group("action"),
            param=float(m.group("param")) if m.group("param") else None,
            nth=nth,
            prob=float(m.group("prob")) if m.group("prob") else None,
            times=times,
        )


@dataclass
class Hit:
    """One triggered rule at one site hit."""

    site: str
    action: str
    rule: Rule
    hit_index: int  # 1-based per-site hit counter value
    rng: random.Random  # deterministic per-(seed, site, hit) decision rng


class Registry:
    """Fault-site registry: site table, rules, per-site hit counters.

    ``check`` is the one decision point: it counts the hit and returns the
    first matching non-exhausted rule (or None). All trigger decisions are
    pure functions of (seed, site, per-site hit index), so any schedule is
    replayable from the seed even when sites interleave across threads.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sites: dict[str, str] = dict(CANONICAL_SITES)
        self._rules: dict[str, list[Rule]] = {}
        self._hits: dict[str, int] = {}
        self.seed = 0
        self.active = False
        self._programmatic = False  # rules came from configure(), not env
        # Replaceable so in-process tests can observe a "crash" as an
        # exception instead of losing the pytest process.
        self.crash_fn = lambda: os._exit(1)

    # --- configuration -----------------------------------------------------

    def register(self, site: str, description: str = "") -> str:
        with self._lock:
            self._sites.setdefault(site, description)
        return site

    def sites(self) -> dict[str, str]:
        with self._lock:
            return dict(self._sites)

    def configure(self, specs, seed: int | None = None,
                  _from_env: bool = False) -> None:
        """Replace all rules with ``specs`` (Rule objects or spec strings)
        and reset hit counters, so a run is replayable from scratch."""
        rules: dict[str, list[Rule]] = {}
        for s in specs:
            r = s if isinstance(s, Rule) else Rule.parse(s)
            rules.setdefault(r.site, []).append(r)
        with self._lock:
            self._rules = rules
            self._hits = {}
            for rs in rules.values():
                for r in rs:
                    r.fired = 0
                    self._sites.setdefault(r.site, "")
            if seed is not None:
                self.seed = seed
            self.active = bool(rules)
            self._programmatic = bool(rules) and not _from_env

    def install_from_env(self) -> None:
        """(Re)load TMTPU_FAULTS / TMTPU_FAULT_SEED. Called at import and
        again from node startup so subprocess runs always start from hit
        counter zero. An explicit env spec wins; with NOTHING in the env,
        rules installed in-process via configure() are left untouched (an
        in-process chaos harness that starts a Node must not have its
        schedule silently wiped)."""
        spec = os.environ.get("TMTPU_FAULTS", "")
        seed = int(os.environ.get("TMTPU_FAULT_SEED", "0") or 0)
        specs = [t for t in spec.split(",") if t.strip()]
        if not specs and self._programmatic:
            return
        self.configure(specs, seed=seed, _from_env=True)

    def clear(self) -> None:
        self.configure([])

    def reset(self, seed: int | None = None) -> None:
        """Zero hit counters and rule fired-counts (same rules): replay."""
        with self._lock:
            self._hits = {}
            for rs in self._rules.values():
                for r in rs:
                    r.fired = 0
            if seed is not None:
                self.seed = seed

    # --- the decision point ------------------------------------------------

    def snapshot(self) -> tuple[dict[str, int], dict[tuple[str, str], int]]:
        """Observability: (per-site hit counts, fired counts keyed
        (site, action)). Consumed by the node metrics sampler so chaos runs
        are visible on the /metrics route."""
        with self._lock:
            hits = dict(self._hits)
            fired: dict[tuple[str, str], int] = {}
            for site, rs in self._rules.items():
                for r in rs:
                    if r.fired:
                        key = (site, r.action)
                        fired[key] = fired.get(key, 0) + r.fired
        return hits, fired

    def check(self, site: str) -> Hit | None:
        if not self.active:
            return None
        with self._lock:
            rules = self._rules.get(site)
            if not rules:
                return None
            idx = self._hits.get(site, 0) + 1
            self._hits[site] = idx
            for r in rules:
                if r.times is not None and r.fired >= r.times:
                    continue
                rng = random.Random(f"{self.seed}:{site}:{idx}")
                if r.nth is not None:
                    if idx < r.nth:
                        continue
                elif r.prob is not None and rng.random() >= r.prob:
                    continue
                r.fired += 1
                return Hit(site=site, action=r.action, rule=r,
                           hit_index=idx, rng=rng)
        return None


REGISTRY = Registry()


# ---------------------------------------------------------------------------
# Site-shaped helpers (what instrumented modules actually call)
# ---------------------------------------------------------------------------


def register(site: str, description: str = "") -> str:
    return REGISTRY.register(site, description)


def sites() -> dict[str, str]:
    return REGISTRY.sites()


def configure(specs, seed: int | None = None) -> None:
    REGISTRY.configure(specs, seed=seed)


def install_from_env() -> None:
    REGISTRY.install_from_env()


def clear() -> None:
    REGISTRY.clear()


def reset(seed: int | None = None) -> None:
    REGISTRY.reset(seed=seed)


def check(site: str) -> Hit | None:
    return REGISTRY.check(site)


def snapshot() -> tuple[dict[str, int], dict[tuple[str, str], int]]:
    return REGISTRY.snapshot()


def _apply(hit: Hit) -> None:
    if hit.action == "crash":
        REGISTRY.crash_fn()
        raise FaultInjected(hit.site)  # crash_fn was stubbed to return
    if hit.action == "raise":
        raise FaultInjected(hit.site)
    if hit.action == "disconnect":
        raise FaultDisconnect(hit.site)
    if hit.action == "delay":
        time.sleep(hit.rule.param if hit.rule.param is not None else 0.05)
        return
    # torn/partial at a non-write site, drop at a non-message site: the
    # schedule is misconfigured. A chaos rule that silently injects nothing
    # would validate nothing -- fail loudly instead.
    raise FaultError(
        f"action {hit.action!r} is not supported at site {hit.site!r}")


def fire(site: str, local: str = "", remote: str = "") -> None:
    """Apply any triggered crash/raise/disconnect/delay rule at ``site``.
    Write-shaped (torn/partial) and message-shaped (drop) actions need the
    site-specific helpers below; a firing that lands here raises
    FaultError so a misconfigured schedule can never pass silently.

    ``local``/``remote`` carry peer-id context at the p2p sites; when
    given, the peer-scoped nemesis plane (utils/nemesis.py) is consulted
    after the global site rules (a dial across a partition raises
    FaultInjected here)."""
    hit = REGISTRY.check(site)
    if hit is not None:
        _apply(hit)
    if local or remote:
        from tendermint_tpu.utils import nemesis

        if nemesis.PLANE.active:
            nemesis.PLANE.outcome(site, local, remote)


def maybe_drop(site: str) -> bool:
    """Message sites (p2p.send/p2p.recv): True when the message should be
    silently discarded; delay sleeps first; disconnect/crash/raise apply."""
    hit = REGISTRY.check(site)
    if hit is None:
        return False
    if hit.action == "drop":
        return True
    _apply(hit)
    return False


def link_outcome(site: str, local: str = "", remote: str = "",
                 channel: int | None = None) -> str:
    """Message sites with peer-id context (MConnection send/recv): the
    global site rules fire first (exact :func:`maybe_drop` semantics),
    then the peer-scoped nemesis plane. Returns ``'pass'``, ``'drop'``,
    or ``'dup'`` (deliver twice); delay rules sleep here; disconnect
    raises FaultDisconnect for the connection error path."""
    if maybe_drop(site):
        return "drop"
    from tendermint_tpu.utils import nemesis

    if not nemesis.PLANE.active:
        return "pass"
    return nemesis.PLANE.outcome(site, local, remote, channel)


def mutate_value(site: str, value: bytes | None) -> bytes | None:
    """Record-read sites (store.*.load): apply a bit-rot / truncation rule
    to the value on its way out of the DB — what the integrity envelope
    (store/envelope.py) exists to catch. Returns the value unchanged when
    no rule fires; missing rows (None) never consume a hit (a row that is
    not there cannot rot).

    * ``bitrot``   -- flip ONE deterministic bit (``~p`` pins the byte
      index; otherwise seeded from (seed, site, hit)).
    * ``truncate`` -- cut the value to a deterministic prefix, possibly
      empty (``~p`` pins the cut length).
    * ``drop``     -- the record reads back as missing.
    * crash/raise/delay apply as at any other site.
    """
    if value is None:
        return None
    hit = REGISTRY.check(site)
    if hit is None:
        return value
    if hit.action == "bitrot":
        if not value:
            return value
        if hit.rule.param is not None:
            pos = min(int(hit.rule.param), len(value) - 1)
            bit = 0
        else:
            pos = hit.rng.randrange(len(value))
            bit = hit.rng.randrange(8)
        return value[:pos] + bytes([value[pos] ^ (1 << bit)]) + value[pos + 1:]
    if hit.action == "truncate":
        if hit.rule.param is not None:
            cut = min(int(hit.rule.param), len(value))
        else:
            cut = hit.rng.randrange(len(value)) if value else 0
        return value[:cut]
    if hit.action == "drop":
        return None
    _apply(hit)
    return value


def corrupt_db(db, key: bytes, mode: str = "bitrot", seed: int = 0) -> bytes:
    """Offline at-rest mutation: deterministically bit-rot or truncate the
    stored value at ``key`` IN the DB (the scrub matrix and the soak
    ``bitrot`` perturbation drive this — a live rule mutates reads in
    flight, this damages the bytes on disk). Returns the original value so
    a harness can assert the repaired row is byte-identical."""
    raw = db.get(key)
    if raw is None:
        raise FaultError(f"corrupt_db: no record at key {key!r}")
    rng = random.Random(f"{seed}:corrupt_db:{mode}:{key.hex()}")
    if mode == "bitrot":
        pos = rng.randrange(len(raw))
        bit = rng.randrange(8)
        db.set(key, raw[:pos] + bytes([raw[pos] ^ (1 << bit)]) + raw[pos + 1:])
    elif mode == "truncate":
        db.set(key, raw[:rng.randrange(len(raw))])
    else:
        raise FaultError(f"corrupt_db: unknown mode {mode!r} "
                         "(want bitrot|truncate)")
    return raw


def tear_wal_tail(wal_dir: str, mode: str = "torn", seed: int = 0) -> int:
    """Offline WAL-tail damage: cut the LAST frame of the newest chunk in
    ``wal_dir`` — `corrupt_db`'s sibling for the consensus WAL. The live
    ``wal.write`` torn/partial rules reproduce a power cut mid-append on a
    running node; this one damages an ABANDONED home (the fabric's
    hard-kill path tears the tail after the incarnation is gone, so a
    reboot must drive `WAL._repair` exactly as a real torn crash would).

    ``torn`` re-cuts the final frame inside its body (header intact, body
    short); ``partial`` cuts inside the 8 header bytes. Returns the number
    of bytes removed (0 when the log has no frame to tear)."""
    if mode not in ("torn", "partial"):
        raise FaultError(f"tear_wal_tail: unknown mode {mode!r} "
                         "(want torn|partial)")
    chunks = sorted(name for name in os.listdir(wal_dir)
                    if name.startswith("wal.") and name[4:].isdigit())
    if not chunks:
        return 0
    path = os.path.join(wal_dir, chunks[-1])
    with open(path, "rb") as f:
        data = f.read()
    # find the last frame boundary with the WAL's own validity scan
    from tendermint_tpu.consensus import wal as cwal

    last_start = None
    end = 0
    for pos, fend, _t, _m in cwal._valid_frames(data):
        last_start, end = pos, fend
    if last_start is None or end < len(data):
        return 0  # empty log, or the tail is already damaged
    frame = data[last_start:end]
    if len(frame) < 2:
        return 0
    rng = random.Random(f"{seed}:tear_wal_tail:{mode}:{chunks[-1]}")
    if mode == "partial":
        cut = rng.randint(1, min(7, len(frame) - 1))
    else:
        cut = rng.randint(min(8, len(frame) - 1), len(frame) - 1)
    with open(path, "wb") as f:
        f.write(data[:last_start] + frame[:cut])
        f.flush()
        os.fsync(f.fileno())
    return len(frame) - cut


def torn_write(site: str, fobj, frame: bytes) -> None:
    """Write sites (WAL append): on a torn/partial rule, write a
    deterministic prefix of ``frame``, push it to disk, and crash -- the
    on-disk tail is exactly what a power cut mid-append leaves. Returns
    normally when no rule fires (the caller then writes the full frame).

    ``torn`` cuts inside the frame body (a valid-looking header with a
    short body); ``partial`` cuts inside the first 8 header bytes. ``~p``
    overrides the cut byte."""
    hit = REGISTRY.check(site)
    if hit is None:
        return
    if hit.action in ("torn", "partial"):
        if hit.rule.param is not None:
            cut = max(1, min(int(hit.rule.param), len(frame) - 1))
        elif hit.action == "partial":
            cut = hit.rng.randint(1, min(7, len(frame) - 1))
        else:
            cut = hit.rng.randint(min(8, len(frame) - 1), len(frame) - 1)
        fobj.write(frame[:cut])
        try:
            fobj.flush()
            os.fsync(fobj.fileno())
        except (OSError, ValueError):
            pass
        REGISTRY.crash_fn()
        raise FaultInjected(site)  # crash_fn was stubbed to return
    _apply(hit)


def crash_point(site: str) -> None:
    """Crash-class site: apply crash/raise/delay rules (alias of fire with
    a name that reads right at commit-path call sites)."""
    fire(site)


# ---------------------------------------------------------------------------
# Legacy utils/fail.py contract (reference: libs/fail/fail.go:10-38)
# ---------------------------------------------------------------------------

_legacy_counter = 0


def fail_point(site: str | None = None) -> None:
    """Set TMTPU_FAIL_INDEX=N to make the N-th fail_point() call in the
    process exit hard, simulating a crash between commit steps (exact
    utils/fail.py semantics, counter shared across all call sites). When a
    ``site`` name is given the named-site rules fire too."""
    global _legacy_counter
    target = os.environ.get("TMTPU_FAIL_INDEX")
    if target is not None:
        if _legacy_counter == int(target):
            REGISTRY.crash_fn()
        _legacy_counter += 1
    if site is not None:
        fire(site)


# Environment config is live from import: child processes (crash matrix
# subprocesses, e2e nodes) inherit TMTPU_FAULTS and need no wiring call.
REGISTRY.install_from_env()
