"""AutoFile group: size-rotated append-only file set (reference:
libs/autofile/group.go:54 + autofile.go).

Layout matches the reference: the active chunk is `<base>`, rotated chunks
are `<base>.000`, `<base>.001`, ... Total size is bounded by
group_check_duration'd head rotation + max chunk retention. The consensus
WAL embeds its own variant of this (consensus/wal.py); this is the
general-purpose util for any append log.
"""

from __future__ import annotations

import os
import threading


class Group:
    """reference: libs/autofile/group.go:54."""

    def __init__(self, head_path: str, head_size_limit: int = 10 * 1024 * 1024,
                 total_size_limit: int = 1024 * 1024 * 1024):
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.total_size_limit = total_size_limit
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._mtx = threading.Lock()
        self._head = open(head_path, "ab")

    # --- naming -------------------------------------------------------------

    def _chunk_path(self, index: int) -> str:
        return f"{self.head_path}.{index:03d}"

    def chunk_indexes(self) -> list[int]:
        """Sorted indexes of rotated chunks on disk."""
        base = os.path.basename(self.head_path)
        d = os.path.dirname(self.head_path) or "."
        out = []
        for name in os.listdir(d):
            if name.startswith(base + "."):
                suffix = name[len(base) + 1:]
                if suffix.isdigit():
                    out.append(int(suffix))
        return sorted(out)

    # --- writing ------------------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._mtx:
            self._head.write(data)
            if self._head.tell() >= self.head_size_limit:
                self._rotate_locked()

    def flush(self, fsync: bool = False) -> None:
        with self._mtx:
            self._head.flush()
            if fsync:
                os.fsync(self._head.fileno())

    def _rotate_locked(self) -> None:
        """Head becomes the next numbered chunk (reference: group.go
        RotateFile)."""
        self._head.flush()
        os.fsync(self._head.fileno())
        self._head.close()
        idxs = self.chunk_indexes()
        nxt = (idxs[-1] + 1) if idxs else 0
        os.rename(self.head_path, self._chunk_path(nxt))
        self._head = open(self.head_path, "ab")
        self._enforce_total_limit_locked()

    def _enforce_total_limit_locked(self) -> None:
        """Drop oldest chunks past the total size limit (reference:
        group.go checkTotalSizeLimit)."""
        if self.total_size_limit <= 0:
            return
        chunks = self.chunk_indexes()
        sizes = {i: os.path.getsize(self._chunk_path(i)) for i in chunks}
        total = sum(sizes.values()) + os.path.getsize(self.head_path)
        for i in chunks:
            if total <= self.total_size_limit:
                break
            os.unlink(self._chunk_path(i))
            total -= sizes[i]

    # --- reading ------------------------------------------------------------

    def read_all(self):
        """Yield the group's bytes in order: oldest chunk first, head last."""
        with self._mtx:
            self._head.flush()
        for i in self.chunk_indexes():
            with open(self._chunk_path(i), "rb") as f:
                yield f.read()
        with open(self.head_path, "rb") as f:
            yield f.read()

    def close(self) -> None:
        with self._mtx:
            self._head.flush()
            self._head.close()
