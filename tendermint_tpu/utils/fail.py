"""Crash-injection points (reference: libs/fail/fail.go:10-38).

Superseded by the deterministic fault-injection subsystem in
utils/faults.py; kept as a compat shim so existing call sites and the
TMTPU_FAIL_INDEX contract (the N-th fail_point() call in the process exits
hard) keep working unchanged. New choke points should use named sites via
tendermint_tpu.utils.faults."""

from __future__ import annotations

from tendermint_tpu.utils.faults import (  # noqa: F401
    FaultDisconnect,
    FaultError,
    FaultInjected,
    fail_point,
)
