"""Crash-injection points (reference: libs/fail/fail.go:10-38).

Set TMTPU_FAIL_INDEX=N to make the N-th fail_point() call in the process
exit hard (os._exit), simulating a crash between commit steps for
crash-consistency tests (reference call sites: state/execution.go:149-196,
consensus/state.go:1605-1685)."""

from __future__ import annotations

import os

_counter = 0


def fail_point() -> None:
    global _counter
    target = os.environ.get("TMTPU_FAIL_INDEX")
    if target is None:
        return
    if _counter == int(target):
        os._exit(1)
    _counter += 1
