"""Batched hashing: ctypes bindings for csrc/hash_batch.c with a hashlib
fallback.

The shared library is built lazily with g++ on first use. The output filename
embeds a content hash of the C sources, so a stale binary can never be loaded
silently (and no binary artifact is committed — csrc/*.so is gitignored). All
entry points take/return numpy arrays so a 20k-signature commit pays ONE FFI
crossing instead of 20k hashlib calls.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_SRC_PATHS = [
    os.path.abspath(os.path.join(_CSRC, "hash_batch.c")),
    os.path.abspath(os.path.join(_CSRC, "sr25519_strobe.c")),
]
_HDR_PATH = os.path.abspath(os.path.join(_CSRC, "hash_consts.h"))


def _lib_path() -> str:
    h = hashlib.sha256()
    for p in _SRC_PATHS + [_HDR_PATH]:
        with open(p, "rb") as f:
            h.update(f.read())
    return os.path.abspath(
        os.path.join(_CSRC, f"libhashbatch-{h.hexdigest()[:12]}.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_U8P = ctypes.POINTER(ctypes.c_uint8)
_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)


def _build(lib_path: str) -> bool:
    tmp = lib_path + ".tmp"
    # -march=native unlocks the 4-way AVX2 SHA-512 lanes in hash_batch.c
    for flags in (["-fopenmp", "-march=native"], ["-march=native"],
                  ["-fopenmp"], []):
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-x", "c", *_SRC_PATHS,
               "-o", tmp] + flags
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
            if r.returncode == 0:
                os.replace(tmp, lib_path)  # atomic vs concurrent builders
                return True
        except (OSError, subprocess.TimeoutExpired):
            return False
    return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("TM_TPU_DISABLE_CHASH") == "1":
            return None
        try:
            lib_path = _lib_path()
            if not os.path.exists(lib_path) and not _build(lib_path):
                return None
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        lib.sha512_batch.argtypes = [_U8P, _I64P, _I32P, ctypes.c_int64, _U8P]
        lib.sha512_rab_batch.argtypes = [
            _U8P, ctypes.c_int64, _U8P, ctypes.c_int64,
            _U8P, _I64P, _I32P, ctypes.c_int64, _U8P,
        ]
        lib.sha256_batch.argtypes = [_U8P, _I64P, _I32P, ctypes.c_int64, _U8P]
        lib.sha256_batch_fixed.argtypes = [
            _U8P, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64, _U8P]
        lib.sr25519_challenge_batch.argtypes = [
            _U8P, ctypes.c_int32, ctypes.c_int32,
            _U8P, _I64P, _I32P, _U8P, _U8P, ctypes.c_int64, _U8P,
        ]
        _lib = lib
        return _lib


def _u8(a: np.ndarray):
    return a.ctypes.data_as(_U8P)


def available() -> bool:
    return _load() is not None


def sha512_rab(r32: np.ndarray, a32: np.ndarray, msgs: list[bytes]) -> np.ndarray:
    """SHA-512(r32[i] || a32[i] || msgs[i]) for every i -> (N, 64) uint8.

    r32, a32: C-contiguous (N, 32) uint8 arrays."""
    n = len(msgs)
    out = np.empty((n, 64), dtype=np.uint8)
    lib = _load()
    if lib is None:
        rb, ab = r32.tobytes(), a32.tobytes()
        for i, m in enumerate(msgs):
            d = hashlib.sha512(rb[32 * i:32 * i + 32] + ab[32 * i:32 * i + 32] + m)
            out[i] = np.frombuffer(d.digest(), dtype=np.uint8)
        return out
    data = b"".join(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int32, count=n)
    offs = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, dtype=np.uint8)
    # NOTE: this host has a single CPU core in the target environment, so
    # thread-fanning the (GIL-releasing) C call buys nothing -- measured.
    lib.sha512_rab_batch(
        _u8(r32), 32, _u8(a32), 32, _u8(buf),
        offs.ctypes.data_as(_I64P), lens.ctypes.data_as(_I32P), n, _u8(out))
    return out


def sha512_many(msgs: list[bytes]) -> np.ndarray:
    n = len(msgs)
    out = np.empty((n, 64), dtype=np.uint8)
    lib = _load()
    if lib is None:
        for i, m in enumerate(msgs):
            out[i] = np.frombuffer(hashlib.sha512(m).digest(), dtype=np.uint8)
        return out
    data = b"".join(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int32, count=n)
    offs = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, dtype=np.uint8)
    lib.sha512_batch(_u8(buf), offs.ctypes.data_as(_I64P),
                     lens.ctypes.data_as(_I32P), n, _u8(out))
    return out


def sha256_many(msgs: list[bytes]) -> np.ndarray:
    n = len(msgs)
    out = np.empty((n, 32), dtype=np.uint8)
    lib = _load()
    if lib is None:
        for i, m in enumerate(msgs):
            out[i] = np.frombuffer(hashlib.sha256(m).digest(), dtype=np.uint8)
        return out
    data = b"".join(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int32, count=n)
    offs = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, dtype=np.uint8)
    lib.sha256_batch(_u8(buf), offs.ctypes.data_as(_I64P),
                     lens.ctypes.data_as(_I32P), n, _u8(out))
    return out


def sr25519_challenges(prefix_state: bytes, prefix_pos: int,
                       prefix_pos_begin: int, msgs: list[bytes],
                       pubs: np.ndarray, rs: np.ndarray) -> np.ndarray | None:
    """Batched schnorrkel verify challenges -> (N, 64) uint8 pre-reduction
    transcript PRF bytes, or None when the C library is unavailable (caller
    falls back to the pure-Python transcript).

    prefix_state/pos/pos_begin: the Strobe state of the transcript prefix
    shared by every signature (SigningContext + empty context label), computed
    once in Python. pubs, rs: C-contiguous (N, 32) uint8 arrays."""
    lib = _load()
    if lib is None:
        return None
    n = len(msgs)
    out = np.empty((n, 64), dtype=np.uint8)
    data = b"".join(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int32, count=n)
    offs = np.zeros(n, dtype=np.int64)
    np.cumsum(lens[:-1], out=offs[1:])
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, dtype=np.uint8)
    st = np.frombuffer(prefix_state, dtype=np.uint8)
    lib.sr25519_challenge_batch(
        _u8(st), prefix_pos, prefix_pos_begin, _u8(buf),
        offs.ctypes.data_as(_I64P), lens.ctypes.data_as(_I32P),
        _u8(pubs), _u8(rs), n, _u8(out))
    return out


def sha256_fixed(rows: np.ndarray) -> np.ndarray:
    """SHA-256 of every row of a C-contiguous (N, W) uint8 array -> (N, 32)."""
    n, w = rows.shape
    out = np.empty((n, 32), dtype=np.uint8)
    lib = _load()
    if lib is None:
        rb = rows.tobytes()
        for i in range(n):
            out[i] = np.frombuffer(
                hashlib.sha256(rb[w * i:w * (i + 1)]).digest(), dtype=np.uint8)
        return out
    lib.sha256_batch_fixed(_u8(rows), w, w, n, _u8(out))
    return out
