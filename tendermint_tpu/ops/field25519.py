"""GF(2^255-19) arithmetic in TPU-friendly limb form.

Field elements are int32 arrays of shape (..., 20): radix-2^13 limbs,
value = sum(limb[i] * 2**(13*i)), 260 bits of headroom over the 255-bit field.

Why radix 2^13 / int32: TPUs have no native int64; the VPU's fast integer path
is int32. Products of 13-bit limbs are < 2^26, and the 20-term convolution plus
the 2^260 === 608 (mod p) fold stays below 2^31 (bounds below), so the whole
multiplier runs in exact int32 arithmetic with zero wide-word emulation.

Bound discipline (the invariant every stored element satisfies):

    NORM: all limbs in [0, 9500)        ("loosely normalized")

* mul(a, b) requires NORM inputs, returns limbs <= 8799.
* add(a, b) requires NORM inputs, returns limbs <= 9409.
* sub(a, b) requires NORM inputs, returns limbs <= 9409.

Bound proof for mul with M = 9500: products <= M^2 = 9.03e7; low-convolution
c_k sums <= 20 terms -> 1.81e9; the high half d_k (<= 19 M^2) is split
d = hi*2^13 + lo then folded as 608*lo (<= 5.0e6) and 608*hi (<= 1.27e8);
c_k + 608*lo_k + 608*hi_{k-1} <= 1.94e9 < 2^31 - 1. Values are only fully
reduced mod p at encode/compare time (to_canonical).

Ops are written to keep the traced HLO graph small (vectorized limb axes,
sequential only where carries force it), since a full verify chains ~3-4k
field muls.

Reference semantics served: the scalar path tendermint_tpu/crypto/ed25519.py
(itself mirroring Go crypto/ed25519; reference crypto/ed25519/ed25519.go:148).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

P = 2**255 - 19
NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1  # 8191
# 2^260 = 2^5 * 2^255 === 32*19 = 608 (mod p): fold factor for limbs 20+.
FOLD = 608

_P_CANON = [(P >> (RADIX * i)) & MASK for i in range(NLIMB)]
P_LIMBS = np.array(_P_CANON, dtype=np.int32)


def _fat_multiple_of_p() -> np.ndarray:
    """Limb vector m with sum(m_i 2^13i) == 64*p and EVERY limb >= 9500, so
    a + m - b is limb-wise non-negative for any NORM b (limbs < 9500).

    Built by borrow-redistribution: start from the unconstrained radix-2^13
    split of 64p (top limb 2^14-1 = 16383 since 64p = 2^261 - 1216), then for
    any limb below 9500 add 2^13 and borrow 1 from the limb above."""
    v = 64 * P
    m = [(v >> (RADIX * i)) & MASK for i in range(NLIMB - 1)]
    m.append(v >> (RADIX * (NLIMB - 1)))  # unmasked top: 16383
    for i in range(NLIMB - 1):
        while m[i] < 9500:
            m[i] += MASK + 1
            m[i + 1] -= 1
    assert all(x >= 9500 for x in m) and m[NLIMB - 1] < (1 << 15)
    assert sum(x << (RADIX * i) for i, x in enumerate(m)) == 64 * P
    return np.array(m, dtype=np.int32)


# Fat-limb multiple of p for limb-wise subtraction without negatives.
PSUB_LIMBS = _fat_multiple_of_p()


def from_int(x: int) -> np.ndarray:
    """Python int -> canonical limb vector (numpy int32, shape (20,))."""
    x %= P
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], dtype=np.int32)


def to_int(limbs) -> int:
    """Limb vector (shape (20,)) -> Python int (not reduced mod p)."""
    arr = np.asarray(limbs)
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMB))


def zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros(tuple(shape) + (NLIMB,), dtype=jnp.int32)


def _carry_once(e):
    """One sequential carry pass + top fold. Accepts limbs < 2^31 - 2^27,
    returns limbs <= max(9409, 8191 + input_carry_spill) -- see callers."""
    out = []
    carry = jnp.zeros_like(e[..., 0])
    for k in range(NLIMB):
        v = e[..., k] + carry
        carry = v >> RADIX
        out.append(v & MASK)
    # carry = overflow past limb 19 (weight 2^260): fold by 608.
    o0 = out[0] + carry * FOLD
    c0 = o0 >> RADIX
    out[0] = o0 & MASK
    out[1] = out[1] + c0
    return jnp.stack(out, axis=-1)


def carry(e):
    """Full renormalization to NORM (limbs <= 8799): two passes."""
    return _carry_once(_carry_once(e))


def add(a, b):
    """a + b. NORM in -> limbs <= 9409 out."""
    return _carry_once(a + b)


def sub(a, b):
    """a - b mod p via a + 64p(fat limbs) - b: limb-wise non-negative for
    NORM b. Max pre-carry limb < 9500 + 16384 < 2^14.7; one pass (with its
    limb-0 fold step) leaves limbs <= 9409."""
    m = jnp.asarray(PSUB_LIMBS)
    return _carry_once(a + m - b)


def mul(a, b):
    """Limb-convolution multiply + fold. NORM in, limbs <= 8799 out.

    Vectorized shift-accumulate keeps this at ~100 HLO ops instead of the
    naive 400 scalar products."""
    shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    conv = jnp.zeros(shape + (2 * NLIMB - 1,), dtype=jnp.int32)
    for i in range(NLIMB):
        conv = conv.at[..., i : i + NLIMB].add(a[..., i : i + 1] * b)
    c = conv[..., :NLIMB]
    d = conv[..., NLIMB:]  # 19 entries, weights 2^260.. -> fold by 608
    lo = d & MASK
    hi = d >> RADIX
    e = c.at[..., : NLIMB - 1].add(FOLD * lo)
    e = e.at[..., 1:NLIMB].add(FOLD * hi)
    return carry(e)


def mul_small(a, c: int):
    """a * c for a small positive Python int (c <= ~220000 keeps 9500*c < 2^31)."""
    return _carry_once(a * jnp.int32(c))


def square(a):
    return mul(a, a)


def nsquare(a, n: int):
    """a^(2^n) with a rolled loop to keep the graph small."""
    if n <= 2:
        for _ in range(n):
            a = square(a)
        return a
    return jax.lax.fori_loop(0, n, lambda _, x: square(x), a)


def inv(a):
    """a^(p-2): standard curve25519 addition chain (11 muls, 254 squarings)."""
    z2 = square(a)
    z9 = mul(a, nsquare(z2, 2))
    z11 = mul(z2, z9)
    z_5_0 = mul(z9, square(z11))          # 2^5 - 2^0
    z_10_0 = mul(nsquare(z_5_0, 5), z_5_0)
    z_20_0 = mul(nsquare(z_10_0, 10), z_10_0)
    z_40_0 = mul(nsquare(z_20_0, 20), z_20_0)
    z_50_0 = mul(nsquare(z_40_0, 10), z_10_0)
    z_100_0 = mul(nsquare(z_50_0, 50), z_50_0)
    z_200_0 = mul(nsquare(z_100_0, 100), z_100_0)
    z_250_0 = mul(nsquare(z_200_0, 50), z_50_0)
    return mul(nsquare(z_250_0, 5), z11)  # 2^255 - 21


def pow_p58(a):
    """a^((p-5)/8) = a^(2^252 - 3): the exponentiation inside ristretto255 /
    ed25519 square-root-ratio computations. Same ladder as inv() up to
    z_250_0, then two squarings and one multiply."""
    z2 = square(a)
    z9 = mul(a, nsquare(z2, 2))
    z11 = mul(z2, z9)
    z_5_0 = mul(z9, square(z11))
    z_10_0 = mul(nsquare(z_5_0, 5), z_5_0)
    z_20_0 = mul(nsquare(z_10_0, 10), z_10_0)
    z_40_0 = mul(nsquare(z_20_0, 20), z_20_0)
    z_50_0 = mul(nsquare(z_40_0, 10), z_10_0)
    z_100_0 = mul(nsquare(z_50_0, 50), z_50_0)
    z_200_0 = mul(nsquare(z_100_0, 100), z_100_0)
    z_250_0 = mul(nsquare(z_200_0, 50), z_50_0)
    return mul(nsquare(z_250_0, 2), a)  # 2^252 - 4, then +1 -> 2^252 - 3


def to_canonical(a):
    """Fully reduce NORM limbs to the canonical representative < p.

    NORM value < 2^260 ~= 32p. Fold bits >= 255 by 19 (twice, for re-carry),
    then up to two conditional subtractions of p."""
    for _ in range(2):
        top = a[..., NLIMB - 1]
        a = a.at[..., NLIMB - 1].set(top & 0xFF)
        a = a.at[..., 0].add((top >> 8) * 19)
        a = _carry_once(a)
    p_limbs = jnp.asarray(P_LIMBS)
    for _ in range(2):
        diff = []
        borrow = jnp.zeros_like(a[..., 0])
        for k in range(NLIMB):
            v = a[..., k] - p_limbs[k] - borrow
            borrow = (v < 0).astype(jnp.int32)
            diff.append(v + borrow * (MASK + 1))
        diff = jnp.stack(diff, axis=-1)
        a = jnp.where((borrow == 0)[..., None], diff, a)
    return a


def eq(a, b):
    """Element-wise field equality of canonical representations."""
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """where(cond, a, b) broadcasting cond over the limb axis."""
    return jnp.where(cond[..., None], a, b)
