"""Vectorized (numpy) arithmetic mod L, the ed25519 group order.

The round-1 host-prep bottleneck was a per-signature Python loop doing
`int.from_bytes(sha512(...)) % L` and per-int window decomposition
(~426 ms for 8k signatures). This module replaces all of it with batched
numpy over the whole signature batch:

 * `reduce_mod_l`:  (N, 64) uint8 little-endian 512-bit values -> canonical
   (N, 32) little-endian representatives mod L, via repeated folding of the
   identity 2^252 === -DELTA (mod L) on radix-2^21 int64 limb vectors.
 * `comb_windows`:  (N, 32) uint8 scalars -> (N, 64) 4-bit comb windows in
   kernel processing order (see ops/ed25519_batch for the comb evaluation).
 * `lt_l`:          vectorized s < L canonicality check (RFC 8032 rule the
   scalar path applies before any curve math; reference
   crypto/ed25519/ed25519.go:148 via edwards25519.ScalarSet canonicality).

L = 2^252 + DELTA where DELTA = 27742317777372353535851937790883648493.
"""

from __future__ import annotations

import numpy as np

L = 2**252 + 27742317777372353535851937790883648493
DELTA = L - 2**252

RADIX = 21
NLIMB = 25  # 25 * 21 = 525 >= 512 bits

# DELTA < 2^125 -> 6 radix-2^21 limbs.
DELTA_LIMBS = np.array(
    [(DELTA >> (RADIX * i)) & ((1 << RADIX) - 1) for i in range(6)], dtype=np.int64
)
assert sum(int(d) << (RADIX * i) for i, d in enumerate(DELTA_LIMBS)) == DELTA

_L_BYTES_BE = np.frombuffer(L.to_bytes(32, "big"), dtype=np.uint8).astype(np.int16)

_BIT_W21 = (1 << np.arange(RADIX, dtype=np.int64))


def bytes_to_limbs_t(b: np.ndarray) -> np.ndarray:
    """(N, 64) uint8 little-endian -> (25, N) int64 radix-2^21 limbs.

    Limb-major layout: every limb is a contiguous (N,) row, so the fold /
    carry loops below run on contiguous memory."""
    words = np.ascontiguousarray(b).view(np.uint64).T.copy()  # (8, N)
    n = words.shape[1]
    out = np.zeros((NLIMB, n), dtype=np.int64)
    mask = np.uint64((1 << RADIX) - 1)
    for j in range(NLIMB):
        w, s = divmod(RADIX * j, 64)
        if w >= 8:
            break
        v = words[w] >> np.uint64(s)
        if s + RADIX > 64 and w + 1 < 8:
            v = v | (words[w + 1] << np.uint64(64 - s))
        out[j] = (v & mask).astype(np.int64)
    return out


def _carry_signed_t(x: np.ndarray, top: int = NLIMB) -> np.ndarray:
    """Full floor-carry propagation on limb rows 0..top-1: rows 0..top-2 end
    in [0, 2^21); row top-1 absorbs the (possibly negative) top residue.
    Sequential over limbs (a negative carry must ripple all the way up in one
    call), vectorized over the batch. Rows >= top must already be zero."""
    carry = np.zeros(x.shape[1], dtype=np.int64)
    for k in range(top):
        t = x[k] + carry
        carry = t >> RADIX  # arithmetic shift = floor division
        x[k] = t - (carry << RADIX)
    x[top - 1] += carry << RADIX  # value-preserving top residue
    return x


def reduce_mod_l(values_le: np.ndarray) -> np.ndarray:
    """(N, 64) uint8 LE 512-bit values -> (N, 32) uint8 canonical LE mod L.

    Fold loop: write v = hi * 2^252 + lo (2^252 = 2^(21*12), an exact limb
    boundary) and replace v by lo - DELTA * hi, an exact congruence mod L.
    Magnitude shrinks ~127 bits per fold; after 4 folds v is canonical in
    [0, L) (range walk-through: 2^512 -> (-2^385, 2^252) -> [0, 2^258) ->
    (-2^131, 2^252) -> [0, L))."""
    x = bytes_to_limbs_t(values_le)
    # (n_hi_limbs, carry_top) per fold, from the range walk-through above:
    # fold 1 consumes 13 hi limbs (v < 2^512), later folds far fewer.
    for nhi, top in ((13, 19), (7, 13), (1, 13), (1, 13)):
        hi = x[12 : 12 + nhi].copy()  # signed limbs of v >> 252
        x[12 : 12 + nhi] = 0
        # x -= conv(DELTA_LIMBS, hi): 6 shifted vector multiplies.
        for i in range(6):
            x[i : i + nhi] -= DELTA_LIMBS[i] * hi
        x = _carry_signed_t(x, top)
    # canonical: limbs in [0, 2^21), value < L < 2^253; repack to 32 LE bytes
    words = np.zeros((4, x.shape[1]), dtype=np.uint64)
    ux = x[:13].astype(np.uint64)  # limbs 0..12 cover value < 2^253
    for j in range(13):
        w, s = divmod(RADIX * j, 64)
        words[w] |= ux[j] << np.uint64(s)
        if s + RADIX > 64 and w + 1 < 4:
            words[w + 1] |= ux[j] >> np.uint64(64 - s)
    return np.ascontiguousarray(words.T).view(np.uint8)


def lt_bound(s_le: np.ndarray, bound_be: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian values < bound? -> (N,) bool.

    bound_be: the bound's 32 big-endian bytes as int16. Vectorized
    big-endian byte compare: the first differing byte decides."""
    s_be = s_le[:, ::-1].astype(np.int16)
    diff = s_be - bound_be
    nz = diff != 0
    first = np.argmax(nz, axis=1)
    first_diff = np.take_along_axis(diff, first[:, None], axis=1)[:, 0]
    return np.where(nz.any(axis=1), first_diff < 0, False)


def lt_l(s_le: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian -> (N,) bool: s < L."""
    return lt_bound(s_le, _L_BYTES_BE)


def comb_windows(scalar_le: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 LE scalars -> (N, 64) int32 comb windows, processing
    order (window for bit-column 63 first).

    Comb(t=4, d=64): bits b_0..b_255 split into 4 blocks of 64; window
    w_i = b_i + 2*b_{64+i} + 4*b_{128+i} + 8*b_{192+i}. Evaluation (see
    ed25519_batch): acc <- 2*acc + T[w_i] for i = 63..0, where
    T[w] = sum_j w_j * [2^(64j)] P."""
    bits = np.unpackbits(np.ascontiguousarray(scalar_le), axis=1, bitorder="little")
    w = bits[:, 0:64] + (bits[:, 64:128] << 1)  # uint8 adds; max value 15
    w += bits[:, 128:192] << 2
    w += bits[:, 192:256] << 3
    return np.ascontiguousarray(w[:, ::-1])  # uint8: H2D stays small
