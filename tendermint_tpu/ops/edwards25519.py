"""Batched twisted-Edwards point arithmetic on device.

Points are (X, Y, Z, T) extended homogeneous coordinates stored as a single
int32 array of shape (..., 4, 20) (limb layout per field25519). The addition
law is the unified a=-1 twisted Edwards formula ("add-2008-hwcd-3"), which is
COMPLETE for all points of curve25519 (a = -1 is square mod p, d non-square),
so identity / small-order inputs need no special-casing - crucial on TPU where
data-dependent branches are unavailable.

Bounds: every mul input below is the output of add/sub/mul/mul_small, all of
which return limbs <= 9409 < 9500 = the NORM bound field25519.mul requires.

Mirrors the scalar reference tendermint_tpu/crypto/ed25519.py:_add/_double
(semantics of Go crypto/ed25519 internals; reference crypto/ed25519/ed25519.go).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops import field25519 as fe

P = fe.P
D = (-121665 * pow(121666, P - 2, P)) % P
TWO_D_LIMBS = fe.from_int(2 * D % P)

# identity (0, 1, 1, 0)
IDENTITY_LIMBS = np.stack(
    [fe.from_int(0), fe.from_int(1), fe.from_int(1), fe.from_int(0)]
)  # (4, 20)


def affine_add(p: tuple[int, int], q: tuple[int, int]) -> tuple[int, int]:
    """Host-side exact affine Edwards addition (complete formula) for
    building precomputed tables; (0, 1) is the identity."""
    x1, y1 = p
    x2, y2 = q
    den = D * x1 * x2 * y1 * y2 % P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + den, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - den, P - 2, P) % P
    return (x3, y3)


def identity(shape=()) -> jnp.ndarray:
    return jnp.broadcast_to(
        jnp.asarray(IDENTITY_LIMBS), tuple(shape) + (4, 20)
    ).astype(jnp.int32)


def from_affine(x: int, y: int) -> np.ndarray:
    """Host-side: affine ints -> extended limb point (4, 20)."""
    return np.stack(
        [fe.from_int(x), fe.from_int(y), fe.from_int(1), fe.from_int(x * y % P)]
    )


def negate_affine(x: int, y: int) -> np.ndarray:
    return from_affine((-x) % P, y)


def add(p, q):
    """Unified extended addition. p, q: (..., 4, 20) -> (..., 4, 20)."""
    X1, Y1, Z1, T1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    X2, Y2, Z2, T2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe.mul(fe.sub(Y1, X1), fe.sub(Y2, X2))
    b = fe.mul(fe.add(Y1, X1), fe.add(Y2, X2))
    c = fe.mul(fe.mul(T1, T2), jnp.asarray(TWO_D_LIMBS))
    d = fe.mul_small(fe.mul(Z1, Z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def double(p):
    """Dedicated doubling (dbl-2008-hwcd)."""
    X1, Y1, Z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe.square(X1)
    b = fe.square(Y1)
    c = fe.mul_small(fe.square(Z1), 2)
    h = fe.add(a, b)
    e = fe.sub(h, fe.square(fe.add(X1, Y1)))
    g = fe.sub(a, b)
    f = fe.add(c, g)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def compress_canonical(p):
    """(..., 4, 20) -> (y_limbs canonical (..., 20), sign (...,) int32).

    The canonical RFC 8032 encoding is y (fully reduced < p, little-endian)
    with the parity of x in the top bit; returned here in limb+sign form for
    direct comparison against a signature's R bytes."""
    zinv = fe.inv(p[..., 2, :])
    x = fe.to_canonical(fe.mul(p[..., 0, :], zinv))
    y = fe.to_canonical(fe.mul(p[..., 1, :], zinv))
    return y, x[..., 0] & 1
