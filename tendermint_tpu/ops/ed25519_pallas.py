"""Pallas TPU kernel for batched ed25519 verification.

Same math as ops/ed25519_batch._verify_kernel (comb evaluation of
[s]B + [h](-A), canonical-encoding compare) but fused into ONE TPU kernel so
the point state never leaves VMEM. Layout choices:

 * batch on the LANE axis: field elements are (20, T) int32 tiles (limb rows
   x T signatures), so every field op is a full-width VPU op. The jnp path's
   (N, 20) layout wastes 108 of 128 lanes.
 * vectorized carries: instead of a 20-step sequential carry chain, each pass
   computes all carries at once and shifts them down one limb row (with the
   2^260 === 608 fold wrapping row 19 -> row 0). Pass counts per op are fixed
   by worst-case bound analysis (see _carry_n).
 * per-key comb tables come in NIELS form (16 entries x 3 field elements
   y+x | y-x | 2dxy = 60 rows/entry, 960 rows x T lanes), gathered from the
   device-resident KeySet cache by validator index - nothing per-key is
   rebuilt per call, and each table addition is a 7-mul mixed add. The
   fixed-base comb table for B is baked in as niels constants the same way.

Bound discipline matches ops/field25519: all stored limbs < 9500, products
and 20-term accumulations stay below 2^31 in int32 (squaring's doubled
cross-products included: 10 * 9500 * 19000 + 9500^2 + fold < 2^31).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tendermint_tpu.ops import ed25519_batch as edb
from tendermint_tpu.ops import edwards25519 as ed
from tendermint_tpu.ops import field25519 as fe
from tendermint_tpu.ops import scalar25519 as sc_mod

import os

MASK = fe.MASK
FOLD = fe.FOLD
NLIMB = fe.NLIMB
P = fe.P
# Lanes per grid step (multiple of 128). 256 measured best on v5e; larger
# tiles spill VMEM (TILE=512 benched 2.6x slower end to end).
TILE = int(os.environ.get("TM_TPU_PALLAS_TILE", "256"))
if TILE % 128 != 0 or TILE <= 0:
    raise ValueError(f"TM_TPU_PALLAS_TILE must be a positive multiple of 128, got {TILE}")

_PSUB = np.asarray(fe.PSUB_LIMBS, dtype=np.int32).reshape(NLIMB, 1)
_P_CANON = np.asarray(fe.P_LIMBS, dtype=np.int32).reshape(NLIMB, 1)
_TWO_D = np.asarray(fe.from_int(2 * ed.D % P), dtype=np.int32).reshape(NLIMB, 1)


# Fixed-base niels comb table: TAB_B[w] = (y+x, y-x, 2dxy) of the comb point
# sum_j w_j [2^(64j)] B (shared with the jnp path's extended-coordinate form).
def _build_b_niels() -> np.ndarray:
    out = np.zeros((16, 3, NLIMB), dtype=np.int32)
    for w, (x, y) in enumerate(edb._B_COMB_AFFINE):
        out[w, 0] = fe.from_int((y + x) % P)
        out[w, 1] = fe.from_int((y - x) % P)
        out[w, 2] = fe.from_int(2 * ed.D * x * y % P)
    return out


_TAB_B = _build_b_niels()

# Pallas kernels may not capture array constants; everything per-lane-uniform
# is packed into one (1020, 1) int32 input: rows 0-19 = 64p limbs, 20-39 =
# canonical p limbs, 40-59 = 2d limbs, 60-1019 = the 16x3x20 B niels table.
CONSTS = np.concatenate(
    [_PSUB, _P_CANON, _TWO_D, _TAB_B.reshape(960, 1)], axis=0
).astype(np.int32)

# Trace-time context: set at kernel entry to slices of the consts ref so the
# field helpers below can use them without captures.
_CTX: dict = {}


# --- field ops on (20, T) int32 values --------------------------------------


def _carry_n(e, n: int):
    """n vectorized carry passes. Each pass: split rows into low 13 bits +
    carries, shift carries down one row, fold row-19 carry into row 0 by 608.

    Pass counts (worst-case bound analysis, mirrors ops/field25519 docstring):
      mul/sq output (<= 1.95e9): 4 passes -> rows <= 8799
      sub output (<= 25881):  2 passes -> rows <= 8799
      2x  output (<= 17598):  1 pass   -> rows <= 9407
      add output (<= 19000):  1 pass   -> rows <= 9407
    """
    for _ in range(n):
        c = e >> 13
        e = e & MASK
        e = e + jnp.concatenate([c[19:20] * FOLD, c[:19]], axis=0)
    return e


def _fold39(conv):
    """(39, T) convolution -> carried (20, T) via the 2^260 === 608 fold."""
    t = conv.shape[1]
    zrow = jnp.zeros((1, t), dtype=jnp.int32)
    c = conv[:NLIMB]
    d = conv[NLIMB:]
    lo = d & MASK
    hi = d >> 13
    c = c + jnp.concatenate([FOLD * lo, zrow], axis=0)
    c = c + jnp.concatenate([zrow, FOLD * hi], axis=0)
    return _carry_n(c, 4)


def _mul(a, b):
    """(20,T) x (20,T) -> (20,T), inputs NORM (<9500), output <= 8799.

    Shift-accumulate via concatenation (Pallas TPU lowering has no scatter;
    static concats lower cleanly)."""
    t = a.shape[1]
    zrow = jnp.zeros((1, t), dtype=jnp.int32)
    conv = None
    for i in range(NLIMB):
        prod = a[i : i + 1] * b  # (20, T)
        shifted = jnp.concatenate(
            [zrow] * i + [prod] + [zrow] * (NLIMB - 1 - i), axis=0
        )  # (39, T)
        conv = shifted if conv is None else conv + shifted
    return _fold39(conv)


def _sq(a):
    """Dedicated squaring: ~half the multiplies of _mul via doubled
    cross-products. Bound: worst conv coeff <= 10*9500*19000 + 9500^2 =
    1.895e9; + fold terms < 1.45e8 -> < 2.04e9 < 2^31."""
    t = a.shape[1]
    zrow = jnp.zeros((1, t), dtype=jnp.int32)
    a2 = a * 2  # limbs <= 19000, no carry needed before the products
    conv = None
    for i in range(NLIMB):
        # rows i+i .. i+19: a_i * [a_i, 2a_{i+1}, ..., 2a_{19}]
        parts = [a[i : i + 1]]
        if i + 1 < NLIMB:
            parts.append(a2[i + 1 :])
        row = jnp.concatenate(parts, axis=0)  # (20 - i, T)
        prod = a[i : i + 1] * row
        shifted = jnp.concatenate(
            [zrow] * (2 * i) + [prod] + [zrow] * (NLIMB - 1 - i), axis=0
        )  # (39, T)
        conv = shifted if conv is None else conv + shifted
    return _fold39(conv)


def _add(a, b):
    return _carry_n(a + b, 1)


def _sub(a, b):
    """a + 64p(fat limbs, every limb >= 9500) - b: limb-wise non-negative."""
    return _carry_n(a + _CTX["psub"] - b, 2)


def _dbl_limb(a):
    return _carry_n(a * 2, 1)


# --- point ops: points are (X, Y, Z, T) tuples of (20, T) -------------------


def _pt_double(p):
    X, Y, Z, _ = p
    a = _sq(X)
    b = _sq(Y)
    c = _dbl_limb(_sq(Z))
    h = _add(a, b)
    e = _sub(h, _sq(_add(X, Y)))
    g = _sub(a, b)
    f = _add(c, g)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _pt_add(p, q):
    """Complete extended addition (both operands full points)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = _mul(_sub(Y1, X1), _sub(Y2, X2))
    b = _mul(_add(Y1, X1), _add(Y2, X2))
    c = _mul(_mul(T1, T2), _CTX["two_d"])
    d = _dbl_limb(_mul(Z1, Z2))
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _pt_madd_niels(p, ypx, ymx, txy2d):
    """Mixed add with a niels-form affine point (y+x, y-x, 2dxy): 7 muls."""
    X1, Y1, Z1, T1 = p
    a = _mul(_sub(Y1, X1), ymx)
    b = _mul(_add(Y1, X1), ypx)
    c = _mul(T1, txy2d)
    d = _dbl_limb(Z1)
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _select16(w, table_rows):
    """Per-lane 16-way select via a 4-level binary where-tree (15 selects vs
    31 multiply-accumulate ops). w: (1, T) window index; table_rows: list of
    16 (rows, T')-broadcastable arrays."""
    cur = list(table_rows)
    for bit in range(4):
        m = ((w >> bit) & 1) != 0  # (1, T) bool
        cur = [jnp.where(m, cur[k + 1], cur[k]) for k in range(0, len(cur), 2)]
    return cur[0]


def _inv(a):
    z2 = _sq(a)
    z9 = _mul(a, _sq(_sq(z2)))
    z11 = _mul(z2, z9)
    z_5_0 = _mul(z9, _sq(z11))
    t = z_5_0
    for _ in range(5):
        t = _sq(t)
    z_10_0 = _mul(t, z_5_0)
    t = z_10_0
    for _ in range(10):
        t = _sq(t)
    z_20_0 = _mul(t, z_10_0)
    t = z_20_0
    for _ in range(20):
        t = _sq(t)
    z_40_0 = _mul(t, z_20_0)
    t = z_40_0
    for _ in range(10):
        t = _sq(t)
    z_50_0 = _mul(t, z_10_0)
    t = z_50_0
    for _ in range(50):
        t = _sq(t)
    z_100_0 = _mul(t, z_50_0)
    t = z_100_0
    for _ in range(100):
        t = _sq(t)
    z_200_0 = _mul(t, z_100_0)
    t = z_200_0
    for _ in range(50):
        t = _sq(t)
    z_250_0 = _mul(t, z_50_0)
    t = z_250_0
    for _ in range(5):
        t = _sq(t)
    return _mul(t, z11)


def _to_canonical(a):
    for _ in range(2):
        top = a[19:20]
        a = jnp.concatenate([a[0:1] + (top >> 8) * 19, a[1:19], top & 0xFF], axis=0)
        a = _carry_n(a, 2)
    p_limbs = _CTX["p_canon"]
    for _ in range(2):
        # a - p with borrow propagation (sequential over 20 rows)
        rows = []
        borrow = jnp.zeros_like(a[0:1])
        for k in range(NLIMB):
            v = a[k : k + 1] - p_limbs[k : k + 1] - borrow
            borrow = (v < 0).astype(jnp.int32)
            rows.append(v + borrow * (MASK + 1))
        diff = jnp.concatenate(rows, axis=0)
        a = jnp.where(borrow == 0, diff, a)
    return a


# --- the kernel --------------------------------------------------------------


def _kernel(consts_ref, tab_ref, h_win_ref, s_win_ref, r_y_ref, r_sv_ref, ok_ref):
    t = TILE
    _CTX["psub"] = consts_ref[0:20, :]
    _CTX["p_canon"] = consts_ref[20:40, :]
    _CTX["two_d"] = consts_ref[40:60, :]

    zero = jnp.zeros((20, t), dtype=jnp.int32)
    one = jnp.concatenate(
        [jnp.ones((1, t), dtype=jnp.int32), jnp.zeros((19, t), dtype=jnp.int32)], axis=0
    )
    identity = (zero, one, one, zero)

    def tab_b(k: int, f: int):
        base = 60 + (k * 3 + f) * 20
        return consts_ref[base : base + 20, :]  # (20, 1)

    def body(j, acc):
        acc = _pt_double(acc)
        wh = h_win_ref[pl.ds(j, 1), :]  # (1, T)
        ws = s_win_ref[pl.ds(j, 1), :]
        # comb point of -A: 16-way select over the gathered per-key NIELS
        # table (60 rows/entry; mixed add = 7 muls vs 9 for extended add)
        rows = [tab_ref[k * 60 : k * 60 + 60, :] for k in range(16)]
        pa = _select16(wh, rows)
        acc = _pt_madd_niels(acc, pa[0:20], pa[20:40], pa[40:60])
        # comb point of B from niels constants ((20,1) broadcast over lanes)
        ypx = _select16(ws, [tab_b(k, 0) for k in range(16)])
        ymx = _select16(ws, [tab_b(k, 1) for k in range(16)])
        txy = _select16(ws, [tab_b(k, 2) for k in range(16)])
        acc = _pt_madd_niels(acc, ypx, ymx, txy)
        return acc

    acc = jax.lax.fori_loop(0, 64, body, identity)

    zinv = _inv(acc[2])
    x = _to_canonical(_mul(acc[0], zinv))
    y = _to_canonical(_mul(acc[1], zinv))
    sign = x[0:1] & 1

    r_y = r_y_ref[:, :]
    r_sign = r_sv_ref[0:1, :]
    valid = r_sv_ref[1:2, :]
    y_eq = jnp.all(y == r_y, axis=0, keepdims=True)
    ok = y_eq & (sign == r_sign) & (valid != 0)
    ok_ref[:, :] = ok.astype(jnp.int32)


def _pallas_verify(tab, h_win, s_win, r_y, r_sv, *, interpret=False):
    """tab (960,N) niels rows, h_win (64,N), s_win (64,N), r_y (20,N),
    r_sv (2,N) -> ok (1, N) int32. N must be a multiple of TILE."""
    n = tab.shape[1]
    grid = (n // TILE,)

    def spec(rows):
        return pl.BlockSpec((rows, TILE), lambda i: (0, i), memory_space=pltpu.VMEM)

    consts_spec = pl.BlockSpec(
        (CONSTS.shape[0], 1), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        grid=grid,
        in_specs=[consts_spec, spec(960), spec(64), spec(64), spec(20), spec(2)],
        out_specs=spec(1),
        interpret=interpret,
    )(jnp.asarray(CONSTS), tab, h_win, s_win, r_y, r_sv)


def _r_limbs_device(r32):
    """(32, N) uint8 R bytes -> ((20, N) int32 y limbs of bits 0..254,
    (1, N) int32 sign bit). Runs on device (XLA): the host uploads raw bytes,
    keeping the per-call H2D payload small over slow links."""
    b = r32.astype(jnp.int32)
    sign = b[31:32] >> 7
    b = jnp.concatenate(
        [b[:31], b[31:32] & 0x7F, jnp.zeros((2, b.shape[1]), jnp.int32)], axis=0
    )
    limbs = []
    for j in range(NLIMB):
        k, s = divmod(13 * j, 8)
        v = (b[k] >> s) | (b[k + 1] << (8 - s)) | (b[k + 2] << (16 - s))
        limbs.append(v & 0x1FFF)
    return jnp.stack(limbs), sign


@jax.jit
def verify_kernel_pallas(tab, h_win, s_win, r32, valid):
    """tab (960, N) int32 (pre-gathered niels tables, device-resident);
    h_win/s_win (64, N) uint8; r32 (32, N) uint8; valid (1, N) uint8.
    -> ok (1, N) int32. One upload of packed uint8 per call, one readback."""
    hw = h_win.astype(jnp.int32)
    sw = s_win.astype(jnp.int32)
    r_y, sign = _r_limbs_device(r32)
    r_sv = jnp.concatenate([sign, valid.astype(jnp.int32)], axis=0)
    return _pallas_verify(tab, hw, sw, r_y, r_sv)


# --- device-side mod-L reduction (radix-2^12 int32 limbs) -------------------
#
# Mirrors scalar25519.reduce_mod_l exactly (differential-tested) but runs as
# XLA int32 ops on device, so the host uploads the raw 64-byte SHA-512
# digests and pays no per-signature reduction work. Radix 2^12 because
# 2^252 = 2^(12*21) is an exact limb boundary (the fold identity is
# 2^252 === -DELTA mod L) and 12x12-bit products convolved over DELTA's 11
# limbs stay < 2^31 in int32.

_L_RADIX = 12
_L_NLIMB = 43  # 43 * 12 = 516 >= 512 bits
_DELTA12 = np.array(
    [(sc_mod.DELTA >> (_L_RADIX * i)) & 0xFFF for i in range(11)], dtype=np.int32)
assert sum(int(d) << (_L_RADIX * i) for i, d in enumerate(_DELTA12)) == sc_mod.DELTA


def _digest_to_limbs12(d64):
    """(64, T) uint8 digest columns -> (43, T) int32 radix-2^12 limbs."""
    b = d64.astype(jnp.int32)
    limbs = []
    for j in range(_L_NLIMB):
        k, s = divmod(_L_RADIX * j, 8)
        v = b[k] >> s
        if k + 1 < 64:
            v = v | (b[k + 1] << (8 - s))
        if s + _L_RADIX > 16 and k + 2 < 64:
            v = v | (b[k + 2] << (16 - s))
        limbs.append(v & 0xFFF)
    return jnp.stack(limbs)


def _carry_signed12(x, top: int):
    """Sequential signed floor-carry over rows 0..top-1; row top-1 absorbs
    the (possibly negative) residue (mirrors scalar25519._carry_signed_t)."""
    rows = []
    carry = jnp.zeros_like(x[0])
    for k in range(top):
        t = x[k] + carry
        carry = t >> _L_RADIX  # arithmetic shift = floor division
        rows.append(t - (carry << _L_RADIX))
    rows[top - 1] = rows[top - 1] + (carry << _L_RADIX)
    return jnp.stack(rows + [jnp.zeros_like(x[0])] * (x.shape[0] - top))


def _reduce_mod_l_device(d64):
    """(64, T) uint8 LE 512-bit digests -> (22, T) int32 canonical radix-2^12
    limbs of the value mod L. Same 4-fold walk as the host reduce_mod_l
    (v = hi*2^252 + lo -> lo - DELTA*hi, shrinking ~127 bits per fold); each
    fold's hi covers every limb the previous fold's top residual can reach."""
    x = _digest_to_limbs12(d64)
    delta = [int(v) for v in _DELTA12]
    for nhi, top in ((22, 34), (13, 23), (2, 22), (1, 22)):
        hi = x[21:21 + nhi]
        x = jnp.concatenate(
            [x[:21], jnp.zeros_like(hi), x[21 + nhi:]], axis=0)
        # x -= conv(DELTA12, hi): 11 shifted row-block subtractions.
        for i in range(11):
            x = jnp.concatenate(
                [x[:i], x[i:i + nhi] - delta[i] * hi, x[i + nhi:]], axis=0)
        x = _carry_signed12(x, top)
    return x[:22]


def _windows_from_limbs12(limbs):
    """(22, T) canonical radix-2^12 limbs -> (64, T) int32 comb windows in
    processing order (mirrors scalar25519.comb_windows bit-for-bit)."""
    def bit(i):
        return (limbs[i // _L_RADIX] >> (i % _L_RADIX)) & 1

    rows = []
    for idx in range(64):
        j = 63 - idx
        w = bit(j) | (bit(64 + j) << 1) | (bit(128 + j) << 2) | (bit(192 + j) << 3)
        rows.append(w)
    return jnp.stack(rows)


def _windows_device(s32):
    """(32, T) uint8 LE scalars -> (64, T) int32 comb windows in processing
    order (mirrors scalar25519.comb_windows exactly: w_j = b_j + 2 b_{64+j}
    + 4 b_{128+j} + 8 b_{192+j}, emitted j=63..0). Runs as fused XLA bit
    ops so the host uploads 32 raw bytes per scalar instead of 64 window
    bytes -- H2D payload is the bottleneck over a tunneled chip."""
    b = s32.astype(jnp.int32)
    rows = []
    for i in range(64):
        j = 63 - i
        w = None
        for t in range(4):
            k = j + 64 * t
            bit = (b[k // 8] >> (k % 8)) & 1
            w = bit if w is None else w | (bit << t)
        rows.append(w)
    return jnp.stack(rows)


@jax.jit
def _verify_chunk(tab, h64, s32, r32, valid):
    """One fixed-shape chunk: tab (960, CHUNK) int32 device-resident niels
    tables; h64 (64, CHUNK) uint8 RAW SHA-512 digests (mod-L reduction and
    comb windows both run on device); s32/r32 (32, CHUNK) uint8;
    valid (1, CHUNK) uint8."""
    hw = _windows_from_limbs12(_reduce_mod_l_device(h64))
    sw = _windows_device(s32)
    r_y, sign = _r_limbs_device(r32)
    r_sv = jnp.concatenate([sign, valid.astype(jnp.int32)], axis=0)
    return _pallas_verify(tab, hw, sw, r_y, r_sv)


# Fixed dispatch shape: XLA compiles one executable per input shape, so the
# pallas call always runs at a multiple of CHUNK lanes (small batches pad to
# one CHUNK; large ones loop). A fresh batch size must never trigger a cold
# compile inside the consensus loop.
CHUNK = int(os.environ.get("TM_TPU_PALLAS_CHUNK", str(16 * TILE)))  # 4096
if CHUNK % TILE != 0 or CHUNK <= 0:
    # A non-multiple silently truncates the pallas grid and leaves trailing
    # output lanes unwritten -- wrong verify results, not an error.
    raise ValueError(
        f"TM_TPU_PALLAS_CHUNK must be a positive multiple of TILE={TILE}, got {CHUNK}")


@jax.jit
def pack_bitmap(ok):
    """(1, N) int32 pass/fail lanes -> (N//32,) uint32 bitmask on device.
    Shrinks the tunnel readback 32x (20,480 lanes: 80 KB -> 2.5 KB);
    unpacked host-side by unpack_bitmap (r4 verdict item 2)."""
    b = ok.reshape(-1, 32).astype(jnp.uint32)
    w = jnp.left_shift(jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
    return (b * w).sum(axis=1, dtype=jnp.uint32)


def unpack_bitmap(v: np.ndarray, n: int) -> np.ndarray:
    """(N//32,) uint32 -> (n,) bool."""
    bits = (v[:, None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(-1)[:n].astype(bool)


def dispatch_items_pipelined(ks, key_idx: np.ndarray, items, pub_ok):
    """Chunk-pipelined dispatch: host prep of chunk i+1 overlaps device
    compute of chunk i (dispatches are async). Returns the (1, Npad) int32
    device array WITHOUT fetching -- callers batch the readback. On the
    1-core host this hides min(prep, device) per chunk versus the
    prep-everything-then-dispatch path."""
    from tendermint_tpu.ops import ed25519_batch as edb

    from tendermint_tpu.ops import sha512_jax

    n = len(items)
    use_dev_sha = sha512_jax.enabled()
    if use_dev_sha and any(
            sha512_jax.n_blocks(len(it[1])) > sha512_jax.MAX_DEVICE_BLOCKS
            for it in items):
        # One over-long message would force a C fallback AFTER the eager
        # prep phase — the worst of both paths. Decide up front and keep
        # the interleaved default pipeline instead.
        import warnings

        warnings.warn(
            "TM_TPU_DEVICE_SHA=1 but a message exceeds the device hash's "
            f"{sha512_jax.MAX_DEVICE_BLOCKS * 128}-byte limit; using the "
            "C host hash for this batch", stacklevel=2)
        use_dev_sha = False

    h64_full = None
    preps = None
    if use_dev_sha:
        # Opt-in (TM_TPU_DEVICE_SHA=1): hash the WHOLE batch in one device
        # call and slice digest columns per chunk. Measured slower than the
        # C host hash on the bench host (see ops/sha512_jax docstring) —
        # kept for hosts whose CPU, not the device link, is the bottleneck.
        # This path preps every chunk up front (no prep/compute overlap);
        # the default path below keeps the interleaved pipeline.
        preps = []
        for off in range(0, n, CHUNK):
            sl = slice(off, min(off + CHUNK, n))
            preps.append((sl, edb.prepare_scalars(
                items[sl], pub_ok[sl], windows=False, reduce=False,
                host_hash=False)))
        lanes = max(((n + CHUNK - 1) // CHUNK) * CHUNK, CHUNK)
        r32 = np.concatenate([p["r32"] for _, p in preps])
        pubs = np.concatenate([p["pubs32"] for _, p in preps])
        h64_full = sha512_jax.sha512_rab_device(
            r32, pubs, [it[1] for it in items], lanes)
        assert h64_full is not None  # lengths prechecked above

    outs = []
    for ci, off in enumerate(range(0, n, CHUNK)):
        sl = slice(off, min(off + CHUNK, n))
        s = (preps[ci][1] if preps is not None
             else edb.prepare_scalars(items[sl], pub_ok[sl], windows=False,
                                      reduce=False))
        cn = sl.stop - sl.start
        idx = np.zeros((CHUNK,), dtype=np.int32)
        idx[:cn] = key_idx[sl]

        def pad_cols(x, rows):
            out = np.zeros((rows, CHUNK), dtype=np.uint8)
            out[:, :cn] = x.T if x.ndim == 2 else x[None, :]
            return out

        if h64_full is not None:
            h64 = jax.lax.dynamic_slice_in_dim(h64_full, sl.start, CHUNK, 1)
        else:
            h64 = jnp.asarray(pad_cols(s["h64"], 64))

        tab = ks.gathered_lane(idx)
        outs.append(_verify_chunk(
            tab,
            h64,
            jnp.asarray(pad_cols(s["s32"], 32)),
            jnp.asarray(pad_cols(s["r32"], 32)),
            jnp.asarray(pad_cols(s["valid"].astype(np.uint8), 1)),
        ))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
