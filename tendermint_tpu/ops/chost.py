"""Host-side batched curve25519 verification: ctypes bindings for
csrc/curve25519_host.c.

This is the CPU half of the adaptive kernel/scalar crossover
(crypto/batch.py): the TPU on this class of host sits behind a tunnel with a
~90 ms round-trip sync floor, so batches below a few thousand signatures are
verified here — serial Straus/wNAF for a handful, a Pippenger
random-linear-combination batch check above that — instead of paying the
floor.  Accept/reject is byte-identical to the scalar reference
(crypto/ed25519.py verify / crypto/sr25519.py verify; reference semantics
crypto/ed25519/ed25519.go:148, crypto/sr25519/pubkey.go:10): the RLC check
falls back to per-item serial verification whenever the batch equation
fails, so callers always observe serial decisions.

Build mirrors ops/chash.py: lazy g++, content-hashed .so name (a stale
binary can never load silently; csrc/*.so is gitignored).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_SRC = os.path.abspath(os.path.join(_CSRC, "curve25519_host.c"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False  # an attempt FINISHED (loaded or definitively failed)
_loading = False  # an attempt is IN FLIGHT (inline or background)
_build_thread: threading.Thread | None = None

_U8P = ctypes.POINTER(ctypes.c_uint8)


def _lib_path() -> str:
    h = hashlib.sha256()
    with open(_SRC, "rb") as f:
        h.update(f.read())
    return os.path.abspath(
        os.path.join(_CSRC, f"libcurvehost-{h.hexdigest()[:12]}.so"))


def _build(lib_path: str) -> bool:
    # Sweep temp files abandoned by builders that died mid-compile (crash-
    # injection subprocesses os._exit while the background build thread is
    # in flight). Only temps older than any plausible live build are
    # reaped, so a concurrent builder's in-flight temp is never raced.
    import time as _t

    try:
        for name in os.listdir(_CSRC):
            if ".so.tmp" not in name:
                continue
            p = os.path.join(_CSRC, name)
            try:
                if _t.time() - os.path.getmtime(p) > 900:
                    os.unlink(p)
            except OSError:
                pass
    except OSError:
        pass
    tmp = lib_path + f".tmp{os.getpid()}"
    # gcc, not g++: the source is pure C, and linking libstdc++ into the .so
    # made ITS terminate handler fire during interpreter teardown when node
    # threads were mid-call ("FATAL: exception not rethrown" at exit).
    for cc, flags in (("gcc", ["-march=native"]), ("gcc", []),
                      ("g++", ["-x", "c"])):
        cmd = ([cc, "-O3", "-shared", "-fPIC", "-pthread"] + flags
               + [_SRC, "-o", tmp])
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=180)
            if r.returncode == 0:
                os.replace(tmp, lib_path)  # atomic vs concurrent builders
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def _load() -> ctypes.CDLL | None:
    """Blocking build+load. The lock is held for the whole attempt, so a
    concurrent ensure_available() waits for an in-flight background build
    instead of racing it; _tried flips only when the attempt FINISHES."""
    global _lib, _tried, _loading
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _loading = True
        try:
            _lib = _load_locked()
        except Exception:  # noqa: BLE001 - a failed build means "chost
            # unavailable", never a dead background build thread (available()
            # would return False forever with _tried unset)
            _lib = None
        finally:
            _loading = False
            _tried = True
        return _lib


def _load_locked() -> ctypes.CDLL | None:
    if os.environ.get("TM_TPU_DISABLE_CHOST") == "1":
        return None
    path = _lib_path()
    if not os.path.exists(path) and not _build(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.ed25519h_verify.argtypes = [
        ctypes.c_long, _U8P, _U8P, _U8P, _U8P, _U8P, _U8P,
        ctypes.c_int, _U8P]
    lib.ed25519h_verify.restype = None
    lib.sr25519h_verify.argtypes = lib.ed25519h_verify.argtypes
    lib.sr25519h_verify.restype = None
    lib.ed25519h_selftest.restype = ctypes.c_int
    if lib.ed25519h_selftest() != 1:
        return None
    return lib


def building() -> bool:
    """True while a build/load attempt is in flight -- background thread OR
    an ensure_available() caller building inline under the lock."""
    t = _build_thread
    return _loading or (t is not None and t.is_alive())


def available() -> bool:
    """Non-blocking: True only when the library is already loaded or loads
    without compiling (the content-hashed .so exists). A needed gcc build is
    kicked off ONCE in a background thread and False is returned until it
    lands -- the single-signature verify path and the batch dispatch fall
    back to pure Python meanwhile (ADVICE r5 item 2: the first signature
    check after a source change must not block behind a 3x180 s build)."""
    global _build_thread
    if _lib is not None:
        return True
    if _tried or building():
        return False
    if os.environ.get("TM_TPU_DISABLE_CHOST") == "1":
        return False
    if os.path.exists(_lib_path()):
        return _load() is not None  # dlopen + selftest only: fast
    # A blocking acquire here could wait out a whole inline build started by
    # ensure_available() on another thread; never do that on this path.
    if not _lock.acquire(blocking=False):
        return False
    try:
        if _build_thread is None and not _tried and _lib is None:
            _build_thread = threading.Thread(
                target=_load, name="chost-build", daemon=True)
            _build_thread.start()
    finally:
        _lock.release()
    return False


def ensure_available() -> bool:
    """Blocking variant for callers that WANT to pay the build (warmup-time
    calibration, differential tests): builds+loads inline, or joins the
    in-flight background build."""
    return _load() is not None


def _u8(a: np.ndarray) -> "ctypes._Pointer":
    return a.ctypes.data_as(_U8P)


def _as_rows(x, n: int) -> np.ndarray:
    a = np.ascontiguousarray(x, dtype=np.uint8)
    assert a.shape == (n, 32), a.shape
    return a


def ed25519_verify(pubs: np.ndarray, h32: np.ndarray, s32: np.ndarray,
                   r32: np.ndarray, valid: np.ndarray,
                   mode: int = 2) -> np.ndarray:
    """Batched ed25519 verify on host.  pubs/h32/s32/r32: (n, 32) uint8
    (h32 = SHA-512(R||A||M) mod L, little-endian); valid: (n,) bool from the
    caller's size prechecks.  mode 0=serial, 1=RLC, 2=auto.  -> (n,) bool."""
    lib = _load()
    assert lib is not None
    n = len(valid)
    if n == 0:
        return np.zeros((0,), dtype=bool)
    pubs = _as_rows(pubs, n)
    h32 = _as_rows(h32, n)
    s32 = _as_rows(s32, n)
    r32 = _as_rows(r32, n)
    v = np.ascontiguousarray(valid, dtype=np.uint8)
    seed = np.frombuffer(os.urandom(32), dtype=np.uint8)
    out = np.zeros((n,), dtype=np.uint8)
    lib.ed25519h_verify(n, _u8(pubs), _u8(h32), _u8(s32), _u8(r32), _u8(v),
                        _u8(seed), mode, _u8(out))
    return out.astype(bool)


def ed25519_verify_one(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single-signature fast path for PubKey.verify_signature: ~100 us vs
    the pure-Python reference's ~2 ms. Caller guarantees availability."""
    import hashlib

    if len(pub) != 32 or len(sig) != 64:
        return False
    L = 2**252 + 27742317777372353535851937790883648493
    h = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(),
                       "little") % L
    arr = np.frombuffer(pub + h.to_bytes(32, "little") + sig[32:] + sig[:32],
                        dtype=np.uint8).reshape(4, 32)
    return bool(ed25519_verify(arr[0:1], arr[1:2], arr[2:3], arr[3:4],
                               np.ones((1,), bool), mode=0)[0])


def sr25519_verify_one(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single sr25519 fast path: C strobe challenge (ops/sr25519_batch) +
    C curve verify. Caller guarantees availability."""
    if len(pub) != 32 or len(sig) != 64:
        return False
    from tendermint_tpu.ops import sr25519_batch as srb

    pubs = np.frombuffer(pub, dtype=np.uint8).reshape(1, 32)
    r32 = np.frombuffer(sig[:32], dtype=np.uint8).reshape(1, 32)
    s32 = np.frombuffer(sig[32:], dtype=np.uint8).reshape(1, 32).copy()
    marker = bool(s32[0, 31] & 128)
    s32[0, 31] &= 127
    c32 = srb.challenges([msg], pubs, r32)
    return bool(sr25519_verify(pubs, c32, s32, r32,
                               np.array([marker]), mode=0)[0])


def sr25519_verify(pubs: np.ndarray, c32: np.ndarray, s32: np.ndarray,
                   r32: np.ndarray, valid: np.ndarray,
                   mode: int = 2) -> np.ndarray:
    """Batched sr25519 verify on host.  c32 = merlin challenge mod L
    (from ops/sr25519_batch's C strobe transcripts); s32 = sig[32:] with the
    schnorrkel marker bit already stripped; r32 = sig[:32]; valid covers
    sizes AND the sig[63]&128 marker check."""
    lib = _load()
    assert lib is not None
    n = len(valid)
    if n == 0:
        return np.zeros((0,), dtype=bool)
    pubs = _as_rows(pubs, n)
    c32 = _as_rows(c32, n)
    s32 = _as_rows(s32, n)
    r32 = _as_rows(r32, n)
    v = np.ascontiguousarray(valid, dtype=np.uint8)
    seed = np.frombuffer(os.urandom(32), dtype=np.uint8)
    out = np.zeros((n,), dtype=np.uint8)
    lib.sr25519h_verify(n, _u8(pubs), _u8(c32), _u8(s32), _u8(r32), _u8(v),
                        _u8(seed), mode, _u8(out))
    return out.astype(bool)
