"""Circuit breaker for the accelerator dispatch routes.

Consensus must keep committing with a dead accelerator: when a device
dispatch fails (runtime error, link loss, injected fault), the batch is
re-verified on the host fallback in the same dispatch, the circuit opens,
and every later batch routes straight to the host until a background probe
proves the device answers again. This is the standard degradation shape of
production accelerator serving stacks -- fail fast, fall back, re-probe off
the hot path -- applied to the verify pipeline of ops/ed25519_batch.py and
ops/sr25519_batch.py.

States:
  closed  -- device route allowed (the normal state).
  open    -- device route skipped; after ``cooldown_s`` the next ``allow()``
             launches one background probe. The caller still gets False (the
             probe owns the first device touch), and the circuit re-closes
             only when the probe reports success -- so a flapping device
             costs one probe per cooldown, never a consensus stall.

TM_TPU_BREAKER_COOLDOWN_S overrides the cooldown (read per trip, so tests
can shrink it without re-importing).
"""

from __future__ import annotations

import os
import threading
import time


class CircuitBreaker:
    def __init__(self, name: str, probe=None, cooldown_s: float = 5.0):
        self.name = name
        self.probe = probe  # () -> bool; run in a daemon thread while open
        self.cooldown_default = cooldown_s
        self._lock = threading.Lock()
        self._open = False
        self._open_until = 0.0
        self._probing = False
        self.failures = 0   # lifetime failure count
        self.trips = 0      # closed -> open transitions
        self.last_error: BaseException | None = None
        self.events: list[tuple[float, str]] = []  # (monotonic, event) ring

    def _cooldown(self) -> float:
        v = os.environ.get("TM_TPU_BREAKER_COOLDOWN_S")
        return float(v) if v else self.cooldown_default

    def _event(self, what: str) -> None:
        self.events.append((time.monotonic(), what))
        del self.events[:-64]

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def allow(self) -> bool:
        """True when the device route may run. While open, a cooldown-due
        call launches the background probe (once) and still returns False."""
        with self._lock:
            if not self._open:
                return True
            if (self.probe is not None and not self._probing
                    and time.monotonic() >= self._open_until):
                self._probing = True
                threading.Thread(target=self._run_probe, daemon=True,
                                 name=f"breaker-probe-{self.name}").start()
            return False

    def _run_probe(self) -> None:
        try:
            ok = bool(self.probe())
        except Exception as e:  # noqa: BLE001 - a dead device raises freely
            self.last_error = e
            ok = False
        with self._lock:
            self._probing = False
            if ok:
                self._open = False
                self._event("probe ok: closed")
            else:
                self._open_until = time.monotonic() + self._cooldown()
                self._event("probe failed: still open")

    def record_failure(self, exc: BaseException | None = None) -> None:
        with self._lock:
            self.failures += 1
            self.last_error = exc
            if not self._open:
                self.trips += 1
                self._event(f"opened: {exc!r}")
            self._open = True
            self._open_until = time.monotonic() + self._cooldown()

    def record_success(self) -> None:
        # A success observed on the device route while closed; nothing to
        # change, but keep the hook so dispatch sites stay symmetric.
        pass

    def reset(self) -> None:
        """Force-close (tests)."""
        with self._lock:
            self._open = False
            self._probing = False
            self._open_until = 0.0


def guarded_dispatch(breaker: CircuitBreaker, dispatch_fn, fallback_fn):
    """The one degradation shape both kernel modules share: run
    ``dispatch_fn() -> (dev, finish)`` behind ``breaker``; any dispatch- or
    finish-time failure records on the breaker and re-verifies via
    ``fallback_fn() -> (None, finish)`` in the same call."""
    if not breaker.allow():
        return fallback_fn()
    try:
        dev, finish = dispatch_fn()
    except Exception as e:  # noqa: BLE001 - any device-route failure degrades
        breaker.record_failure(e)
        return fallback_fn()

    def finish_cb(fetched):
        try:
            out = finish(fetched)
        except Exception as e:  # noqa: BLE001
            breaker.record_failure(e)
            _, fb = fallback_fn()
            return fb(None)
        breaker.record_success()
        return out

    return dev, finish_cb


def guarded_fetch(breaker: CircuitBreaker, dev, finish, fallback_fn):
    """verify_batch tail: fetch ``dev`` and resolve, degrading a fetch-time
    device failure through ``fallback_fn`` exactly like a dispatch failure."""
    if dev is None:
        return finish(None)
    import jax

    try:
        fetched = jax.device_get(dev)
    except Exception as e:  # noqa: BLE001
        breaker.record_failure(e)
        _, fb = fallback_fn()
        return fb(None)
    return finish(fetched)
