"""Batched sr25519 (schnorrkel) verification on TPU.

The reference wraps native go-schnorrkel and verifies serially at ~50-100
us/sig (reference: crypto/sr25519/pubkey.go:10); the repo's spec-faithful
pure-Python path (crypto/sr25519.py) costs ~18 ms/sig. This module makes
sr25519 a first-class batched key type by reusing the ed25519 Edwards comb
kernel for the curve work:

    schnorrkel verify:  s*B == R + k*A
    rearranged:         R' = [s]B + [k](-A)  must equal R as ristretto points

which is EXACTLY the ed25519 kernel's comb evaluation shape ([s]B + [h](-A))
with the challenge k in place of h. The three sr25519-specific pieces:

 * merlin transcript challenges k: batched in C (csrc/sr25519_strobe.c, one
   FFI crossing; pure-Python Transcript fallback), reduced mod L with the
   vectorized scalar25519.reduce_mod_l.
 * ristretto255 decode of R: ON DEVICE -- the sqrt-ratio exponentiation
   (field25519.pow_p58) vectorizes over the batch; the host uploads raw R
   bytes only.
 * ristretto equality: coset check X'*y_r == Y'*x_r  OR  Y'*y_r == X'*x_r
   (projective, RFC 9496 4.5) instead of compress-and-compare -- no encode
   needed, 4 field muls.

Accept/reject is byte-identical with crypto/sr25519.verify: the same
structural checks (marker bit, canonical s < L), the same ristretto decode
validity conditions (host-checked canonical field element + device-checked
square/t-sign/y-zero), the same transcript bytes (differential test in
tests/test_sr25519_batch.py).

Pubkey comb tables are cached per validator-set byte sequence exactly like
the ed25519 KeySet (device-resident across heights).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import sr25519 as srref
from tendermint_tpu.ops import breaker as _cbreaker
from tendermint_tpu.ops import ed25519_batch as edb
from tendermint_tpu.utils import faults
from tendermint_tpu.ops import edwards25519 as ed
from tendermint_tpu.ops import field25519 as fe
from tendermint_tpu.ops import scalar25519 as sc
from tendermint_tpu.ops import chash

P = fe.P
L = sc.L

_ONE = fe.from_int(1)
_D_LIMBS = fe.from_int(ed.D)
_SQRT_M1_LIMBS = fe.from_int(srref.SQRT_M1)

_P_BYTES_BE = np.frombuffer(P.to_bytes(32, "big"), dtype=np.uint8).astype(np.int16)


# ---------------------------------------------------------------------------
# Transcript challenges (host)
# ---------------------------------------------------------------------------

_prefix_lock = threading.Lock()
_prefix: tuple[bytes, int, int] | None = None


def _transcript_prefix() -> tuple[bytes, int, int]:
    """Strobe state after Transcript("SigningContext") + append_message("",""),
    shared by every signature; built once with the pure-Python stack."""
    global _prefix
    with _prefix_lock:
        if _prefix is None:
            t = srref.Transcript(b"SigningContext")
            t.append_message(b"", b"")
            s = t.strobe
            _prefix = (bytes(s.state), s.pos, s.pos_begin)
        return _prefix


def challenges(msgs: list[bytes], pubs: np.ndarray, rs: np.ndarray) -> np.ndarray:
    """Per-signature challenge scalars -> (N, 32) uint8 canonical LE mod L.

    pubs, rs: C-contiguous (N, 32) uint8."""
    state, pos, pos_begin = _transcript_prefix()
    wide = chash.sr25519_challenges(state, pos, pos_begin, msgs, pubs, rs)
    if wide is None:
        # Pure-Python fallback: clone the prefix per item.
        wide = np.empty((len(msgs), 64), dtype=np.uint8)
        pb, rb = pubs.tobytes(), rs.tobytes()
        for i, m in enumerate(msgs):
            t = srref.Transcript.__new__(srref.Transcript)
            t.strobe = srref.Strobe128.__new__(srref.Strobe128)
            t.strobe.state = bytearray(state)
            t.strobe.pos, t.strobe.pos_begin, t.strobe.cur_flags = pos, pos_begin, 0
            t.append_message(b"sign-bytes", m)
            t.append_message(b"proto-name", b"Schnorr-sig")
            t.append_message(b"sign:pk", pb[32 * i:32 * i + 32])
            t.append_message(b"sign:R", rb[32 * i:32 * i + 32])
            wide[i] = np.frombuffer(t.challenge_bytes(b"sign:c", 64), dtype=np.uint8)
    return sc.reduce_mod_l(wide)


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------


def _ct_abs(x):
    """|x| mod p: negate when the canonical representative is odd."""
    xc = fe.to_canonical(x)
    neg = (xc[..., 0] & 1) == 1
    return fe.select(neg, fe.sub(jnp.zeros_like(xc), xc), xc)


def _sqrt_ratio_m1(u, v):
    """RFC 9496 4.2 SQRT_RATIO_M1, vectorized (mirrors srref._sqrt_ratio_m1)."""
    sqrt_m1 = jnp.asarray(_SQRT_M1_LIMBS)
    v3 = fe.mul(fe.square(v), v)
    v7 = fe.mul(fe.square(v3), v)
    r = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    check = fe.to_canonical(fe.mul(v, fe.square(r)))
    u_c = fe.to_canonical(u)
    neg_u = fe.to_canonical(fe.sub(jnp.zeros_like(u), u))
    neg_u_i = fe.to_canonical(fe.mul(fe.sub(jnp.zeros_like(u), u), sqrt_m1))
    correct = fe.eq(check, u_c)
    flipped = fe.eq(check, neg_u)
    flipped_i = fe.eq(check, neg_u_i)
    r = fe.select(flipped | flipped_i, fe.mul(r, sqrt_m1), r)
    return correct | flipped, _ct_abs(r)


def _ristretto_decode_dev(s_limbs):
    """(N, 20) canonical field limbs of the 32-byte encoding (host has
    already rejected s >= p and odd s) -> (x, y, ok). Mirrors
    srref.ristretto_decode."""
    shape = s_limbs.shape[:-1]
    one = jnp.broadcast_to(jnp.asarray(_ONE), shape + (20,))
    d = jnp.asarray(_D_LIMBS)
    ss = fe.square(s_limbs)
    u1 = fe.sub(one, ss)
    u2 = fe.add(one, ss)
    u2_sqr = fe.square(u2)
    # v = -(D * u1^2) - u2^2
    v = fe.sub(jnp.zeros_like(ss), fe.add(fe.mul(fe.mul(d, u1), u1), u2_sqr))
    was_square, invsqrt = _sqrt_ratio_m1(one, fe.mul(v, u2_sqr))
    den_x = fe.mul(invsqrt, u2)
    den_y = fe.mul(fe.mul(invsqrt, den_x), v)
    x = _ct_abs(fe.mul(fe.mul_small(s_limbs, 2), den_x))
    y = fe.mul(u1, den_y)
    t_c = fe.to_canonical(fe.mul(x, y))
    y_c = fe.to_canonical(y)
    ok = was_square & ((t_c[..., 0] & 1) == 0) & ~jnp.all(y_c == 0, axis=-1)
    return x, y, ok


def _sr_verify_kernel(tab, k_win, s_win, r_limbs, valid, axis_name=None):
    """The jitted batch verify.

    tab:     (N, 16, 4, 20) int32  comb table of -A per signature (cached)
    k_win:   (N, 64) int32   comb windows of the challenge k
    s_win:   (N, 64) int32   comb windows of s
    r_limbs: (N, 20) int32   field limbs of the sig's 32-byte R encoding
    valid:   (N,)    bool    host-side precheck results
    axis_name: mesh axis when running inside shard_map (marks the loop carry
               as device-varying; same plumbing as the ed25519 twin)
    ->       (N,)    bool
    """
    n = tab.shape[0]
    tab_b = jnp.broadcast_to(jnp.asarray(edb.TAB_B), (n, 16, 4, 20))

    def body(j, acc):
        acc = ed.double(acc)
        wk = jax.lax.dynamic_slice_in_dim(k_win, j, 1, axis=1)[:, 0]
        ws = jax.lax.dynamic_slice_in_dim(s_win, j, 1, axis=1)[:, 0]
        acc = ed.add(acc, edb._gather_point(tab, wk))
        acc = ed.add(acc, edb._gather_point(tab_b, ws))
        return acc

    acc0 = ed.identity((n,))
    if axis_name is not None:
        # pvary deprecated for pcast in jax 0.9; jax < 0.5 needs no marking
        # (varying-manual-axes tracking didn't exist) -- see the ed25519 twin.
        pcast = getattr(jax.lax, "pcast", None)
        if pcast is not None:
            acc0 = pcast(acc0, axis_name, to="varying")
        elif hasattr(jax.lax, "pvary"):
            acc0 = jax.lax.pvary(acc0, axis_name)
    acc = jax.lax.fori_loop(0, 64, body, acc0)

    x_r, y_r, ok_r = _ristretto_decode_dev(r_limbs)
    X, Y = acc[..., 0, :], acc[..., 1, :]
    # Ristretto coset equality of R' = (X:Y:Z) and R = (x_r, y_r), projective:
    # x'*y_r == y'*x_r  OR  y'*y_r == x'*x_r  (RFC 9496 4.5; Z cancels).
    e1 = fe.eq(fe.to_canonical(fe.mul(X, y_r)), fe.to_canonical(fe.mul(Y, x_r)))
    e2 = fe.eq(fe.to_canonical(fe.mul(Y, y_r)), fe.to_canonical(fe.mul(X, x_r)))
    return (e1 | e2) & ok_r & valid


_kernel = jax.jit(_sr_verify_kernel)


# ---------------------------------------------------------------------------
# Pubkey key sets (ristretto decode differs from ed25519 decompress)
# ---------------------------------------------------------------------------

_decode_cache: dict[bytes, np.ndarray | None] = {}


def _decode_neg(pub: bytes) -> np.ndarray | None:
    """Cached: ristretto pubkey bytes -> extended limbs of -A, or None."""
    hit = _decode_cache.get(pub)
    if hit is not None or pub in _decode_cache:
        return hit
    pt = srref.ristretto_decode(pub)
    out = None
    if pt is not None:
        x, y, _, _ = pt
        out = ed.negate_affine(x, y)
    if len(_decode_cache) < 1_000_000:
        _decode_cache[pub] = out
    return out


_KS_LOCK = threading.Lock()
_KS_CACHE: OrderedDict[bytes, tuple[edb.KeySet, np.ndarray]] = OrderedDict()
# unique-key-SET level (see edb.build_keyset): coalesced verify-service
# launches reuse device tables across novel interleavings
_KS_UNIQ_CACHE: OrderedDict[bytes, edb.KeySet] = OrderedDict()


def get_keyset(pubs: list[bytes]) -> tuple[edb.KeySet, np.ndarray, np.ndarray]:
    """-> (KeySet, key_idx (N,) int32, pub_ok (N,) bool); comb tables of the
    ristretto-decoded -A, device-resident, cached by pubkey byte sequence
    (level 1) and by unique-key-set digest (level 2)."""
    return edb.build_keyset(pubs, _KS_CACHE, _KS_LOCK, _decode_neg,
                            uniq_cache=_KS_UNIQ_CACHE)


# ---------------------------------------------------------------------------
# Host prep + dispatch
# ---------------------------------------------------------------------------

_BIT_W = (1 << np.arange(13, dtype=np.int64)).astype(np.int32)


def _bytes_to_limbs(b32: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 LE field-element encodings -> (N, 20) int32 limbs."""
    bits = np.unpackbits(b32, axis=1, bitorder="little").astype(np.int32)
    bits = np.concatenate(
        [bits, np.zeros((bits.shape[0], 4), dtype=np.int32)], axis=1)  # 260
    return (bits.reshape(-1, 20, 13) @ _BIT_W).astype(np.int32)


def _lt_p(s_le: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 LE -> (N,) bool: value < p (canonical field encoding)."""
    return sc.lt_bound(s_le, _P_BYTES_BE)


def _parse_items(items, n: int):
    """-> (sig_ok, marker_ok, r32, s32 (marker stripped), pubs_arr,
    pub_size_ok): the structural prechecks every route shares."""
    sig_ok = np.fromiter(
        (len(it[2]) == srref.SIGNATURE_SIZE for it in items), dtype=bool, count=n)
    zero64 = b"\x00" * 64
    sigs = np.frombuffer(
        b"".join(it[2] if len(it[2]) == 64 else zero64 for it in items),
        dtype=np.uint8).reshape(n, 64)
    r32 = np.ascontiguousarray(sigs[:, :32])
    s32 = np.ascontiguousarray(sigs[:, 32:]).copy()
    marker_ok = (s32[:, 31] & 128) != 0  # schnorrkel v1 marker bit
    s32[:, 31] &= 127
    pubs32, pub_size_ok = edb._normalize_pubs([it[0] for it in items])
    pubs_arr = np.frombuffer(pubs32, dtype=np.uint8).reshape(n, 32)
    return sig_ok, marker_ok, r32, s32, pubs_arr, pub_size_ok


def _scalar_fallback_bitmap(items) -> np.ndarray:
    """Pure-Python serial re-verification (the degradation floor)."""
    return np.fromiter((srref.verify(p, m, s) for (p, m, s) in items),
                       dtype=bool, count=len(items))


def _host_fallback(items, n):
    """(device_out=None, finish) via the C host verifier when loaded, else
    the pure-Python scalar loop."""
    from tendermint_tpu.ops import chost

    if chost.available():
        sig_ok, marker_ok, r32, s32, pubs_arr, pub_size_ok = _parse_items(items, n)
        k32 = challenges([it[1] for it in items], pubs_arr, r32)
        bitmap = chost.sr25519_verify(
            pubs_arr, k32, s32, r32, sig_ok & marker_ok & pub_size_ok)
    else:
        bitmap = _scalar_fallback_bitmap(items)
    return None, lambda _unused: bitmap


def _dispatch_device(items, n: int, multichip: bool = False):
    """The accelerator route proper; raises on device failure (injected or
    real) -- the circuit breaker in dispatch_batch owns the fallback. The
    fault site fires in dispatch_batch, not here, so the breaker probe
    never consumes consensus-path hit indices (see the ed25519 twin)."""
    sig_ok, marker_ok, r32, s32, pubs_arr, _pub_size_ok = _parse_items(items, n)
    ks, key_idx, pub_ok = get_keyset([it[0] for it in items])
    pub_ok = pub_ok & ks.valid[key_idx]
    s_ok = sc.lt_l(s32)
    # R must be a canonical ristretto encoding: s < p and s even (the square
    # test runs on device inside the decode).
    r_ok = _lt_p(r32) & ((r32[:, 0] & 1) == 0)
    valid = sig_ok & marker_ok & s_ok & r_ok & pub_ok

    k32 = challenges([it[1] for it in items], pubs_arr, r32)

    k_win = sc.comb_windows(k32).astype(np.int32)
    s_win = sc.comb_windows(s32).astype(np.int32)
    r_limbs = _bytes_to_limbs(r32)

    if multichip:
        # Multi-chip: the signature axis shards over the ("dp",) mesh, the
        # same routing the ed25519 twin takes (policy in
        # batch_shard.should_shard; comb tables replicate once per set).
        from tendermint_tpu.parallel import batch_shard

        dev = batch_shard.dispatch_sharded(
            "sr25519", ks, key_idx, [k_win, s_win, r_limbs, valid], n)
        edb._start_host_copy(dev)
        return dev, lambda v: np.asarray(v)[:n]

    # Fixed-tile chunking through the one JNP_TILE-shaped executable.
    tile = edb.JNP_TILE
    nb = max(edb._round_up(n, tile), tile)
    idx = np.zeros((nb,), dtype=np.int32)
    idx[:n] = key_idx

    def pad(v):
        out = np.zeros((nb,) + v.shape[1:], dtype=v.dtype)
        out[:n] = v
        return out

    kw, sw, rl, va = pad(k_win), pad(s_win), pad(r_limbs), pad(valid)
    outs = []
    for off in range(0, nb, tile):
        tab = jnp.take(ks.tab_ext, jnp.asarray(idx[off:off + tile]), axis=0)
        outs.append(_kernel(
            tab,
            jnp.asarray(kw[off:off + tile]),
            jnp.asarray(sw[off:off + tile]),
            jnp.asarray(rl[off:off + tile]),
            jnp.asarray(va[off:off + tile]),
        ))
    ok = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    edb._start_host_copy(ok)
    return ok, lambda v: np.asarray(v)[:n]


def _device_probe() -> bool:
    """Circuit-breaker probe: one real signature through the device route
    (breaker background thread, never the consensus path); fires its own
    fault site, ops.sr25519.probe."""
    faults.fire("ops.sr25519.probe")
    priv = srref.gen_priv_key(b"\x7c" * 32)
    items = [(priv.pub_key().data, b"breaker-probe",
              srref.sign(priv.data, b"breaker-probe"))]
    dev, finish = _dispatch_device(items, 1)
    return bool(np.all(finish(jax.device_get(dev))))


BREAKER = _cbreaker.CircuitBreaker("sr25519-device", probe=_device_probe)


def dispatch_batch(items: list[tuple[bytes, bytes, bytes]],
                   force_device: bool = False):
    """Async batched verify (same contract as ed25519_batch.dispatch_batch):
    returns (device_out, finish) with nothing fetched, so mixed-key commits
    overlap the ed25519 and sr25519 readbacks in one device_get.
    force_device=True skips the host route (callers that pipeline
    sub-crossover chunks against device flights). The device route sits
    behind the same circuit-breaker degradation as the ed25519 twin."""
    if not items:
        return None, lambda _: np.zeros((0,), dtype=bool)
    from tendermint_tpu.parallel import batch_shard

    n = len(items)
    multichip = batch_shard.should_shard(n)

    if not multichip and not force_device and n < edb.host_crossover():
        # Same crossover as ed25519: a kernel flush below it loses to the C
        # host verifier (ops/chost does its own ristretto decodes + s<L).
        from tendermint_tpu.ops import chost

        if chost.available() or chost.building():
            # While the C build is in flight this degrades to the pure-
            # Python loop: bounded by the build window, and still cheaper
            # than a cold-process XLA compile of the kernel.
            return _host_fallback(items, n)
    def _device():
        faults.fire("ops.sr25519.device")
        return _dispatch_device(items, n, multichip)

    return _cbreaker.guarded_dispatch(
        BREAKER, _device, lambda: _host_fallback(items, n))


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Batched verify of [(pub, msg, sig)]; returns (len(items),) bool,
    byte-identical accept/reject with crypto/sr25519.verify."""
    dev, finish = dispatch_batch(items)
    return _cbreaker.guarded_fetch(
        BREAKER, dev, finish, lambda: _host_fallback(items, len(items)))
