"""SHA-512 on device (XLA), vectorized over lanes — evaluated, OFF by
default (set TM_TPU_DEVICE_SHA=1 to enable).

The ed25519 batch verifier needs h = SHA-512(R || A || M) per signature
(crypto/ed25519.verify; RFC 8032 step 2). Hashing on host costs ~1 us/sig
of single-core C time (csrc/hash_batch.c) — the last serial term in the
batch path — while the padded messages upload in ~3 ms for a 20k batch,
so moving the hash on-device looked like a ~18 ms win on the headline.

Measured on the v5e chip (20,480-sig commit, 2026-07-30): it is NOT one.
The 80-round compression is scalar-heavy uint32 work the VPU has no
leverage on — per-chunk hashing ran 155 ms vs 145 ms for the C path, and
one whole-batch call ran 218 ms vs 163 ms (the fori_loop's dynamic W/K
indexing dominates; a fully unrolled build compiles for 10+ minutes).
The C SHA-512 therefore stays the default; this module remains as the
evaluated alternative for hosts whose CPU, not PCIe, is the bottleneck.

64-bit words are modeled as (hi, lo) uint32 pairs (TPUs have no native
uint64 lanes); lanes with fewer blocks than the batch maximum freeze
their state via a mask, so one executable serves mixed message lengths.
Differentially tested against hashlib.sha512 across lengths including
the one-block/two-block padding boundary (tests/test_sha512_device.py).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

# FIPS 180-4 round constants: fractional parts of cube roots of the first
# eighty primes, as (hi, lo) uint32 pairs.
_PRIMES: list[int] = []
_c = 2
while len(_PRIMES) < 80:
    if all(_c % p for p in _PRIMES):
        _PRIMES.append(_c)
    _c += 1


def _frac_root(p: int, power: float) -> int:
    from decimal import Decimal, getcontext

    getcontext().prec = 50
    x = Decimal(p) ** (Decimal(1) / Decimal(int(1 / power)))
    return int((x - int(x)) * (1 << 64))


_K64 = [_frac_root(p, 1.0 / 3.0) for p in _PRIMES]
_H0_64 = [_frac_root(p, 0.5) for p in _PRIMES[:8]]
# Sanity: pin against the published constants.
assert _K64[0] == 0x428A2F98D728AE22 and _K64[79] == 0x6C44198C4A475817
assert _H0_64[0] == 0x6A09E667F3BCC908 and _H0_64[7] == 0x5BE0CD19137E2179

_K_HI = np.array([k >> 32 for k in _K64], dtype=np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K64], dtype=np.uint32)


def _rotr(h, l, n):  # noqa: E741 - (hi, lo) pair
    if n < 32:
        return ((h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n)))
    if n == 32:
        return l, h
    m = n - 32
    return ((l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m)))


def _shr(h, l, n):  # noqa: E741 - n < 32 everywhere in SHA-512
    return (h >> n), (l >> n) | (h << (32 - n))


def _add2(ah, al, bh, bl):
    lo = al + bl
    return ah + bh + (lo < al).astype(jnp.uint32), lo


def _add3(ah, al, bh, bl, ch, cl):
    return _add2(*_add2(ah, al, bh, bl), ch, cl)


def _xor2(a, b):
    return a[0] ^ b[0], a[1] ^ b[1]


def _schedule_body(t, w):
    """Extend the message schedule: w is (80, 2, N) uint32."""
    w15 = (w[t - 15, 0], w[t - 15, 1])
    w2 = (w[t - 2, 0], w[t - 2, 1])
    s0 = _xor2(_xor2(_rotr(*w15, 1), _rotr(*w15, 8)), _shr(*w15, 7))
    s1 = _xor2(_xor2(_rotr(*w2, 19), _rotr(*w2, 61)), _shr(*w2, 6))
    wt = _add3(*_add2(w[t - 16, 0], w[t - 16, 1], *s0), w[t - 7, 0],
               w[t - 7, 1], *s1)
    return w.at[t].set(jnp.stack(wt))


def _round_body(t, carry):
    """One compression round: carry is ((8, 2, N) working vars, (80,2,N) w,
    (80,2) k)."""
    v, w, k = carry
    a = (v[0, 0], v[0, 1])
    b = (v[1, 0], v[1, 1])
    c = (v[2, 0], v[2, 1])
    d = (v[3, 0], v[3, 1])
    e = (v[4, 0], v[4, 1])
    f = (v[5, 0], v[5, 1])
    g = (v[6, 0], v[6, 1])
    h = (v[7, 0], v[7, 1])
    S1 = _xor2(_xor2(_rotr(*e, 14), _rotr(*e, 18)), _rotr(*e, 41))
    ch = ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))
    kt = (k[t, 0], k[t, 1])
    wt = (w[t, 0], w[t, 1])
    t1 = _add2(*_add3(*h, *S1, *ch), *_add2(*kt, *wt))
    S0 = _xor2(_xor2(_rotr(*a, 28), _rotr(*a, 34)), _rotr(*a, 39))
    maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
           (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
    t2 = _add2(*S0, *maj)
    new_e = _add2(*d, *t1)
    new_a = _add2(*t1, *t2)
    nv = jnp.stack([
        jnp.stack(new_a), jnp.stack(a), jnp.stack(b), jnp.stack(c),
        jnp.stack(new_e), jnp.stack(e), jnp.stack(f), jnp.stack(g),
    ])
    return nv, w, k


def sha512_blocks(blocks: jnp.ndarray, nblocks: jnp.ndarray) -> jnp.ndarray:
    """blocks: (B*128, N) uint8 — per-lane padded messages, column-major
    lanes; nblocks: (1, N) int32 — how many 128-byte blocks each lane's
    message actually occupies (the rest are zero filler). Returns (64, N)
    uint8 digests. B is static (shape); per-lane block counts are not.

    The schedule extension and 80 rounds run as lax.fori_loops (a fully
    unrolled build compiles for minutes on the TPU toolchain); the per-lane
    uint64 words live as (hi, lo) uint32 pairs throughout.
    """
    total_rows = blocks.shape[0]
    assert total_rows % 128 == 0
    b_max = total_rows // 128
    n = blocks.shape[1]
    u = blocks.astype(jnp.uint32)
    k = jnp.stack([jnp.asarray(_K_HI), jnp.asarray(_K_LO)], axis=1)  # (80,2)
    k = jnp.broadcast_to(k[:, :, None], (80, 2, 1)).astype(jnp.uint32)
    state = jnp.stack([
        jnp.stack([jnp.full((n,), h >> 32, jnp.uint32),
                   jnp.full((n,), h & 0xFFFFFFFF, jnp.uint32)])
        for h in _H0_64])  # (8, 2, N)
    for b in range(b_max):
        base = b * 128
        # W[0..15] from the block bytes, big-endian words.
        w16 = []
        for i in range(16):
            o = base + 8 * i
            hi = (u[o] << 24) | (u[o + 1] << 16) | (u[o + 2] << 8) | u[o + 3]
            lo = (u[o + 4] << 24) | (u[o + 5] << 16) | (u[o + 6] << 8) | u[o + 7]
            w16.append(jnp.stack([hi, lo]))
        w = jnp.concatenate([jnp.stack(w16),
                             jnp.zeros((64, 2, n), jnp.uint32)])
        w = jax.lax.fori_loop(16, 80, _schedule_body, w)
        v, _, _ = jax.lax.fori_loop(
            0, 80, _round_body, (state, w, jnp.broadcast_to(k, (80, 2, n))))
        hi_sum = state[:, 0] + v[:, 0] + (state[:, 1] + v[:, 1] < state[:, 1]
                                          ).astype(jnp.uint32)
        lo_sum = state[:, 1] + v[:, 1]
        new_state = jnp.stack([hi_sum, lo_sum], axis=1)
        active = nblocks[0] > b
        state = jnp.where(active, new_state, state)
    out = []
    for i in range(8):
        for word, sh in ((state[i, 0], 24), (state[i, 0], 16),
                         (state[i, 0], 8), (state[i, 0], 0),
                         (state[i, 1], 24), (state[i, 1], 16),
                         (state[i, 1], 8), (state[i, 1], 0)):
            out.append((word >> sh) & 0xFF)
    return jnp.stack(out).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# Host-side packing of R || A || M with SHA-512 padding
# ---------------------------------------------------------------------------

_PAD_CACHE: dict[tuple[int, int], bytes] = {}


def n_blocks(msg_len: int) -> int:
    """Blocks for a 64 + msg_len byte message (R||A prefix) with padding."""
    return (64 + msg_len + 17 + 127) // 128


def _suffix(msg_len: int, rows: int) -> bytes:
    """0x80 || zeros || 128-bit BE bit length, then zero-fill to `rows`
    total bytes for the 64+msg_len-byte message."""
    key = (msg_len, rows)
    sfx = _PAD_CACHE.get(key)
    if sfx is None:
        total = 64 + msg_len
        padded = n_blocks(msg_len) * 128
        sfx = (b"\x80" + b"\x00" * (padded - total - 17)
               + (8 * total).to_bytes(16, "big")
               + b"\x00" * (rows - padded))
        _PAD_CACHE[key] = sfx
    return sfx


def pack_rab(r32: np.ndarray, pubs: np.ndarray, msgs: list[bytes],
             rows: int) -> tuple[np.ndarray, np.ndarray]:
    """Build ((N, rows) uint8 padded R||A||M buffers, (N,) int32 block
    counts). rows must be a multiple of 128 covering every message."""
    n = len(msgs)
    rb, ab = r32.tobytes(), pubs.tobytes()
    parts = []
    counts = np.empty((n,), dtype=np.int32)
    for i, m in enumerate(msgs):
        parts.append(rb[32 * i : 32 * i + 32])
        parts.append(ab[32 * i : 32 * i + 32])
        parts.append(m)
        parts.append(_suffix(len(m), rows))
        counts[i] = n_blocks(len(m))
    buf = np.frombuffer(b"".join(parts), dtype=np.uint8)
    return buf.reshape(n, rows), counts


# Device SHA-512 handles up to this many blocks; longer messages fall back
# to the C path (csrc/hash_batch.c). Canonical votes are always 2 blocks.
MAX_DEVICE_BLOCKS = 8


def enabled() -> bool:
    """Opt-in: the C host hash measured faster on the bench host (see
    module docstring)."""
    return os.environ.get("TM_TPU_DEVICE_SHA", "0") == "1"


def bucket_blocks(b: int) -> int:
    """Pad the static block dimension to {2, 4, 8} so odd message lengths
    don't each compile a fresh executable."""
    for cap in (2, 4, 8):
        if b <= cap:
            return cap
    raise ValueError(f"{b} blocks exceeds MAX_DEVICE_BLOCKS")


_sha512_blocks_jit = jax.jit(sha512_blocks)


def sha512_rab_device(r32: np.ndarray, pubs: np.ndarray, msgs: list[bytes],
                      lanes: int) -> jnp.ndarray | None:
    """Dispatch SHA-512(R||A||M) for a chunk: returns a (64, lanes) uint8
    device array future, or None when any message is too long for the
    device path (caller falls back to C). `lanes` pads the lane axis;
    trailing pad lanes have nblocks=0 and emit the raw SHA-512 initial
    state, so callers MUST mask them by validity."""
    if not msgs:
        return None
    longest = max(len(m) for m in msgs)
    if n_blocks(longest) > MAX_DEVICE_BLOCKS:
        return None
    b = bucket_blocks(n_blocks(longest))
    rows = b * 128
    buf, counts = pack_rab(r32, pubs, msgs, rows)
    blocks = np.zeros((lanes, rows), dtype=np.uint8)
    blocks[: len(msgs)] = buf
    nb = np.zeros((1, lanes), dtype=np.int32)
    nb[0, : len(msgs)] = counts
    return _sha512_blocks_jit(jnp.asarray(blocks.T), jnp.asarray(nb))
