"""Batched ed25519 verification: the framework's north-star TPU kernel.

Replaces the reference's serial per-signature loop (~70-100us/sig on one CPU
core; reference crypto/ed25519/ed25519.go:148, called from types/vote_set.go:205
and types/validator_set.go:685-826) with one wide SIMD verification:

    host (cheap, per-sig):  size checks, S < L check, A decompress (cached per
                            validator), h = SHA-512(R||A||msg) mod L, nibble
                            decomposition of s and h, R byte -> limb split
    device (the FLOPs):     R' = [s]B + [h](-A)  via shared-doubling Straus
                            with 4-bit windows, then canonical compression and
                            a byte-exact compare against the signature's R.

Accept/reject is byte-identical with the scalar path (crypto/ed25519.py):
 - s >= L rejected (host);
 - non-decodable / non-canonical A rejected (host, same rules as scalar ref);
 - R never decompressed: the comparison is against the canonical encoding of
   R', so non-canonical R bytes fail exactly as in the scalar path;
 - h reduced mod L before the scalar mult (both paths), so small-order A
   components behave identically.

Batches are padded to power-of-two buckets to bound XLA recompiles; results
come back as a boolean bitmap (the analogue of the reference's
libs/bits.BitArray vote bitmap).
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import ed25519 as ref
from tendermint_tpu.ops import edwards25519 as ed
from tendermint_tpu.ops import field25519 as fe

L = ref.L
P = ref.P

MIN_BUCKET = 64

# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------

# Fixed 16-entry window table for the base point B: TAB_B[w] = w*B, extended
# coords, built once on host with exact ints.
def _build_base_table() -> np.ndarray:
    pts = [(0, 1)]  # affine (x, y); identity is (0, 1)
    base = (ref.BASE[0], ref.BASE[1])
    for _ in range(15):
        pts.append(ed.affine_add(pts[-1], base))
    return np.stack([ed.from_affine(x, y) for (x, y) in pts])  # (16, 4, 20)


TAB_B = _build_base_table()


def _gather_point(table, idx):
    """table (N, 16, 4, 20), idx (N,) -> (N, 4, 20)."""
    n = table.shape[0]
    flat = table.reshape(n, 16, 80)
    got = jnp.take_along_axis(flat, idx[:, None, None].astype(jnp.int32), axis=1)
    return got.reshape(n, 4, 20)


def _verify_kernel(a_neg, h_win, s_win, r_y, r_sign, valid, axis_name=None):
    """The jitted batch verify.

    a_neg:  (N, 4, 20) int32   extended coords of -A (host-decompressed)
    h_win:  (N, 64)    int32   4-bit windows of h, most-significant first
    s_win:  (N, 64)    int32   4-bit windows of s, most-significant first
    r_y:    (N, 20)    int32   raw y limbs of sig[:32] (bit 255 stripped)
    r_sign: (N,)       int32   bit 255 of sig[:32]
    valid:  (N,)       bool    host-side precheck results
    axis_name: mesh axis when running inside shard_map (marks the loop carry
               as device-varying; see jax shard-map scan-vma docs)
    ->      (N,)       bool
    """
    n = a_neg.shape[0]

    # Per-signature window table for -A: tab[w] = w * (-A), w = 0..15.
    rows = [ed.identity((n,)), a_neg]
    for w in range(2, 16):
        if w % 2 == 0:
            rows.append(ed.double(rows[w // 2]))
        else:
            rows.append(ed.add(rows[w - 1], a_neg))
    tab_a = jnp.stack(rows, axis=1)  # (N, 16, 4, 20)

    tab_b = jnp.broadcast_to(jnp.asarray(TAB_B), (n, 16, 4, 20))

    def body(j, acc):
        for _ in range(4):
            acc = ed.double(acc)
        wh = jax.lax.dynamic_slice_in_dim(h_win, j, 1, axis=1)[:, 0]
        ws = jax.lax.dynamic_slice_in_dim(s_win, j, 1, axis=1)[:, 0]
        acc = ed.add(acc, _gather_point(tab_a, wh))
        acc = ed.add(acc, _gather_point(tab_b, ws))
        return acc

    acc0 = ed.identity((n,))
    if axis_name is not None:
        acc0 = jax.lax.pvary(acc0, axis_name)
    acc = jax.lax.fori_loop(0, 64, body, acc0)

    y, sign = ed.compress_canonical(acc)
    ok = jnp.all(y == r_y, axis=-1) & (sign == r_sign)
    return ok & valid


# jax.jit caches one executable per input shape (= per padded bucket size).
_jnp_kernel = jax.jit(_verify_kernel)


# ---------------------------------------------------------------------------
# Host-side preparation
# ---------------------------------------------------------------------------

_decomp_cache: dict[bytes, np.ndarray | None] = {}


def _decompress_neg(pub: bytes) -> np.ndarray | None:
    """Cached: pubkey bytes -> extended limbs of -A, or None if invalid."""
    hit = _decomp_cache.get(pub)
    if hit is not None or pub in _decomp_cache:
        return hit
    pt = ref._decompress(pub)
    out = None
    if pt is not None:
        x, y, z, _ = pt
        assert z == 1
        out = ed.negate_affine(x, y)
    if len(_decomp_cache) < 1_000_000:
        _decomp_cache[pub] = out
    return out


def _nibbles_msb_first(x: int) -> np.ndarray:
    """256-bit int -> 64 4-bit windows, most significant first."""
    b = x.to_bytes(32, "big")
    arr = np.frombuffer(b, dtype=np.uint8)
    out = np.empty(64, dtype=np.int32)
    out[0::2] = arr >> 4
    out[1::2] = arr & 15
    return out


_BIT_W = (1 << np.arange(13, dtype=np.int64)).astype(np.int32)


def _r_to_limbs(r32: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, 32) uint8 R bytes -> ((N, 20) raw y limbs, (N,) sign bits)."""
    bits = np.unpackbits(r32, axis=1, bitorder="little")  # (N, 256)
    sign = bits[:, 255].astype(np.int32)
    y_bits = bits[:, :255].astype(np.int32)
    y_bits = np.concatenate(
        [y_bits, np.zeros((y_bits.shape[0], 5), dtype=np.int32)], axis=1
    )  # pad to 260
    limbs = y_bits.reshape(-1, 20, 13) @ _BIT_W
    return limbs.astype(np.int32), sign


def next_bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def prepare(items: list[tuple[bytes, bytes, bytes]]):
    """items: [(pub, msg, sig)] -> dict of padded numpy arrays for the kernel.

    Performs every check the scalar path performs before its scalar mult, so
    entries that fail land in the `valid` mask and the device result for them
    is ignored (they are filled with the identity / zeros)."""
    n = len(items)
    nb = next_bucket(n)
    a_neg = np.zeros((nb, 4, 20), dtype=np.int32)
    a_neg[:] = ed.IDENTITY_LIMBS
    h_win = np.zeros((nb, 64), dtype=np.int32)
    s_win = np.zeros((nb, 64), dtype=np.int32)
    r32 = np.zeros((nb, 32), dtype=np.uint8)
    valid = np.zeros((nb,), dtype=bool)

    for i, (pub, msg, sig) in enumerate(items):
        if len(pub) != ref.PUBKEY_SIZE or len(sig) != ref.SIGNATURE_SIZE:
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            continue
        neg = _decompress_neg(pub)
        if neg is None:
            continue
        h = int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little") % L
        a_neg[i] = neg
        h_win[i] = _nibbles_msb_first(h)
        s_win[i] = _nibbles_msb_first(s)
        r32[i] = np.frombuffer(sig[:32], dtype=np.uint8)
        valid[i] = True

    r_y, r_sign = _r_to_limbs(r32)
    return dict(
        a_neg=a_neg, h_win=h_win, s_win=s_win, r_y=r_y, r_sign=r_sign, valid=valid
    ), n


def _use_pallas() -> bool:
    import os

    mode = os.environ.get("TM_TPU_ED25519_KERNEL", "auto")
    if mode == "pallas":
        return True
    if mode == "jnp":
        return False
    # Pallas TPU lowering only; "axon" is this image's TPU plugin name.
    return jax.default_backend() in ("tpu", "axon")


def verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> np.ndarray:
    """Batched verify of [(pub, msg, sig)]; returns (len(items),) bool.

    Dispatches to the fused Pallas kernel on TPU (ops/ed25519_pallas); the
    pure-jnp path remains as the CPU / fallback implementation."""
    if not items:
        return np.zeros((0,), dtype=bool)
    args, n = prepare(items)
    if _use_pallas():
        from tendermint_tpu.ops import ed25519_pallas

        targs = ed25519_pallas.transpose_args(args)
        ok = ed25519_pallas.verify_kernel_pallas(
            **{k: jnp.asarray(v) for k, v in targs.items()}
        )
        return np.asarray(ok)[0, :n].astype(bool)
    ok = _jnp_kernel(**{k: jnp.asarray(v) for k, v in args.items()})
    return np.asarray(ok)[:n]
