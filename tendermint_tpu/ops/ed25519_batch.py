"""Batched ed25519 verification: the framework's north-star TPU kernel.

Replaces the reference's serial per-signature loop (~70-100us/sig on one CPU
core; reference crypto/ed25519/ed25519.go:148, called from types/vote_set.go:205
and types/validator_set.go:685-826) with one wide SIMD verification:

    host (vectorized over the whole batch; ops/scalar25519, ops/chash):
        size checks, S < L check, batched SHA-512 h = H(R||A||msg), h mod L,
        comb-window decomposition, R byte -> limb split
    device (the FLOPs):     R' = [s]B + [h](-A)  via a comb (Lim-Lee)
        evaluation: 64 shared doublings + 64+64 table additions, then
        canonical compression and a byte-exact compare against the sig's R.

Comb method (t=4 teeth, d=64 columns): scalar bits split into 4 blocks of 64;
T[w] = sum_j w_j * [2^(64j)] P for w in 0..15; evaluation
acc <- 2*acc + T_A[wh_i] + T_B[ws_i] for i = 63..0. This quarters the
doubling count vs per-signature Straus (256 -> 64), the dominant cost. The
per-key tables T_A depend only on the pubkey, so they are built once per
validator set ON DEVICE and cached in HBM across heights (steady-state
consensus re-verifies the same keys every height); per call only the per-sig
scalars/windows move host->device.

Accept/reject is byte-identical with the scalar path (crypto/ed25519.py):
 - s >= L rejected (host);
 - non-decodable / non-canonical A rejected (host, same rules as scalar ref);
 - R never decompressed: the comparison is against the canonical encoding of
   R', so non-canonical R bytes fail exactly as in the scalar path;
 - h reduced mod L before the scalar mult (both paths), so small-order A
   components behave identically.

Batches are padded to power-of-two buckets to bound XLA recompiles; results
come back as a boolean bitmap (the analogue of the reference's
libs/bits.BitArray vote bitmap).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.utils import faults, jaxcache

jaxcache.enable()

from tendermint_tpu.crypto import ed25519 as ref
from tendermint_tpu.ops import breaker as _cbreaker
from tendermint_tpu.ops import chash
from tendermint_tpu.ops import edwards25519 as ed
from tendermint_tpu.ops import scalar25519 as sc

L = ref.L
P = ref.P

MIN_BUCKET = 64

# ---------------------------------------------------------------------------
# Fixed-base comb table for B (host, exact ints)
# ---------------------------------------------------------------------------


def _b_comb_affine() -> list[tuple[int, int]]:
    """T_B[w] = sum_j w_j * [2^(64j)] B as affine points, w = 0..15."""
    base = (ref.BASE[0], ref.BASE[1])
    pj = [base]
    for _ in range(3):
        p = pj[-1]
        for _ in range(64):
            p = ed.affine_add(p, p)
        pj.append(p)
    pts = []
    for w in range(16):
        acc = (0, 1)
        for j in range(4):
            if (w >> j) & 1:
                acc = ed.affine_add(acc, pj[j])
        pts.append(acc)
    return pts


_B_COMB_AFFINE = _b_comb_affine()
# Extended-coordinate form for the jnp kernel: (16, 4, 20).
TAB_B = np.stack([ed.from_affine(x, y) for (x, y) in _B_COMB_AFFINE])


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _gather_point(table, idx):
    """table (N, 16, 4, 20), idx (N,) -> (N, 4, 20)."""
    n = table.shape[0]
    flat = table.reshape(n, 16, 80)
    got = jnp.take_along_axis(flat, idx[:, None, None].astype(jnp.int32), axis=1)
    return got.reshape(n, 4, 20)


def _verify_kernel(tab, h_win, s_win, r_y, r_sign, valid, axis_name=None):
    """The jitted batch verify (pure-jnp path; CPU fallback + shard_map body).

    tab:    (N, 16, 4, 20) int32  comb table of -A per signature (cached)
    h_win:  (N, 64)    int32   comb windows of h, processing order
    s_win:  (N, 64)    int32   comb windows of s, processing order
    r_y:    (N, 20)    int32   raw y limbs of sig[:32] (bit 255 stripped)
    r_sign: (N,)       int32   bit 255 of sig[:32]
    valid:  (N,)       bool    host-side precheck results
    axis_name: mesh axis when running inside shard_map (marks the loop carry
               as device-varying; see jax shard-map scan-vma docs)
    ->      (N,)       bool
    """
    n = tab.shape[0]
    tab_b = jnp.broadcast_to(jnp.asarray(TAB_B), (n, 16, 4, 20))

    def body(j, acc):
        acc = ed.double(acc)
        wh = jax.lax.dynamic_slice_in_dim(h_win, j, 1, axis=1)[:, 0]
        ws = jax.lax.dynamic_slice_in_dim(s_win, j, 1, axis=1)[:, 0]
        acc = ed.add(acc, _gather_point(tab, wh))
        acc = ed.add(acc, _gather_point(tab_b, ws))
        return acc

    acc0 = ed.identity((n,))
    if axis_name is not None:
        # Mark the loop carry device-varying under shard_map (pvary was
        # deprecated in favour of pcast in jax 0.9; jax < 0.5 has neither
        # and needs no marking -- varying-manual-axes tracking didn't exist).
        pcast = getattr(jax.lax, "pcast", None)
        if pcast is not None:
            acc0 = pcast(acc0, axis_name, to="varying")
        elif hasattr(jax.lax, "pvary"):
            acc0 = jax.lax.pvary(acc0, axis_name)
    acc = jax.lax.fori_loop(0, 64, body, acc0)

    y, sign = ed.compress_canonical(acc)
    ok = jnp.all(y == r_y, axis=-1) & (sign == r_sign)
    return ok & valid


# jax.jit caches one executable per input shape (= per padded bucket size).
_jnp_kernel = jax.jit(_verify_kernel)


def _dbl64(p):
    return jax.lax.fori_loop(0, 64, lambda _, q: ed.double(q), p)


# Comb-table recurrence indices: T[w] = T[w ^ lsb(w)] + ps[log2(lsb(w))].
# Rolled into a fori_loop (one traced point-add instead of 15) because the
# unified Edwards formula is complete: T[0] = identity participates safely.
_COMB_PREV = np.array([w ^ (w & -w) for w in range(16)], dtype=np.int32)
_COMB_J = np.array(
    [max((w & -w).bit_length() - 1, 0) for w in range(16)], dtype=np.int32
)


def _build_comb_tables_impl(a_neg):
    """(K, 4, 20) extended -A points -> (K, 16, 4, 20) comb tables."""
    k = a_neg.shape[0]
    ps0 = jnp.zeros((4, k, 4, 20), jnp.int32).at[0].set(a_neg)
    ps = jax.lax.fori_loop(
        0, 3, lambda j, ps: ps.at[j + 1].set(_dbl64(ps[j])), ps0
    )
    prev = jnp.asarray(_COMB_PREV)
    jj = jnp.asarray(_COMB_J)

    def body(w, tab):
        p = jnp.take(tab, prev[w], axis=1)
        return tab.at[:, w].set(ed.add(p, ps[jj[w]]))

    tab0 = (
        jnp.zeros((k, 16, 4, 20), jnp.int32)
        .at[:, 0].set(ed.identity((k,)))
    )
    return jax.lax.fori_loop(1, 16, body, tab0)


_build_comb_tables = jax.jit(_build_comb_tables_impl)

# Fixed compile shapes: XLA compiles one executable per input shape, and a
# cold compile of these limb-heavy graphs is O(30-100 s). Chunking every
# batch through ONE (tile-sized) executable makes compilation a one-time
# cost per process regardless of batch size.
KEY_TILE = int(os.environ.get("TM_TPU_KEY_TILE", "256"))
JNP_TILE = int(os.environ.get("TM_TPU_JNP_TILE", "256"))


def _build_comb_tables_tiled(a_neg: np.ndarray):
    """(K, 4, 20) -> (ceil(K/KEY_TILE)*KEY_TILE, 16, 4, 20), built in
    fixed-shape chunks so _build_comb_tables compiles exactly once."""
    k = a_neg.shape[0]
    kp = max(_round_up(k, KEY_TILE), KEY_TILE)
    padded = np.broadcast_to(ed.IDENTITY_LIMBS, (kp, 4, 20)).copy()
    padded[:k] = a_neg
    chunks = [
        _build_comb_tables(jnp.asarray(padded[o : o + KEY_TILE]))
        for o in range(0, kp, KEY_TILE)
    ]
    return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=0)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@jax.jit
def _gather_transpose(tab_rows, idx):
    """(Kb, R), (nb,) -> (R, nb) lane-major per-item tables.

    Gather along the MAJOR axis then transpose: a lane-axis gather is
    pathologically slow on TPU, a row gather + transpose is fast."""
    rows = jnp.take(tab_rows, idx, axis=0)  # (nb, R)
    return rows.T


@jax.jit
def _to_niels(tab_ext):
    """(Kb, 16, 4, 20) extended comb points -> (Kb, 960) niels rows
    (y+x | y-x | 2dxy per entry, affine via batched Z inversion).

    Niels form turns the kernel's per-entry table add from a 9-mul full
    extended add into a 7-mul mixed add AND shrinks the per-iteration table
    read by 25% (60 rows/entry vs 80). One batched inversion per key set,
    amortized across every height that reuses the set."""
    from tendermint_tpu.ops import field25519 as fe

    X, Y, Z = tab_ext[:, :, 0], tab_ext[:, :, 1], tab_ext[:, :, 2]
    zinv = fe.inv(Z)
    x = fe.mul(X, zinv)
    y = fe.mul(Y, zinv)
    ypx = fe.add(y, x)
    ymx = fe.sub(y, x)
    txy = fe.mul(fe.mul(x, y), jnp.asarray(ed.TWO_D_LIMBS))
    k = tab_ext.shape[0]
    niels = jnp.stack([ypx, ymx, txy], axis=2)  # (Kb, 16, 3, 20)
    return niels.reshape(k, 960)


# ---------------------------------------------------------------------------
# Key sets: per-validator-set comb tables, device-resident across heights
# ---------------------------------------------------------------------------

_decomp_cache: dict[bytes, np.ndarray | None] = {}


def _decompress_neg(pub: bytes) -> np.ndarray | None:
    """Cached: pubkey bytes -> extended limbs of -A, or None if invalid."""
    hit = _decomp_cache.get(pub)
    if hit is not None or pub in _decomp_cache:
        return hit
    pt = ref._decompress(pub)
    out = None
    if pt is not None:
        x, y, z, _ = pt
        assert z == 1
        out = ed.negate_affine(x, y)
    if len(_decomp_cache) < 1_000_000:
        _decomp_cache[pub] = out
    return out


class KeySet:
    """Comb tables for an ordered multiset of pubkeys, cached on device.

    `tab_ext` is (Kb, 16, 4, 20) on device (Kb = K padded to a bucket);
    `tab_lane` is the same data in the Pallas lane-major layout (1280, Kb),
    built lazily. `key_idx` maps item slot -> table row for the pubkey
    sequence this KeySet was BUILT from; callers must use the per-sequence
    key_idx returned by build_keyset/get_keyset (the unique-key-set cache
    reuses one KeySet across many sequences)."""

    __slots__ = ("n_keys", "valid", "tab_ext", "key_idx", "_gathered",
                 "_niels", "replicated")

    def __init__(self, n_keys, valid, tab_ext, key_idx):
        self.n_keys = n_keys
        self.valid = valid
        self.tab_ext = tab_ext
        self.key_idx = key_idx
        self._gathered: OrderedDict = OrderedDict()
        self._niels = None
        # (mesh-devices key, mesh-replicated tab_ext) set by parallel/
        # batch_shard.replicated_tables on the multi-device path.
        self.replicated = None

    def niels_rows(self):
        """(Kb, 960) niels-form comb tables, built on device once per set."""
        if self._niels is None:
            self._niels = _to_niels(self.tab_ext)
        return self._niels

    def gathered_lane(self, idx: np.ndarray):
        """(960, nb) lane-major niels comb tables for a padded index pattern,
        cached per pattern. Steady-state commit verification reuses the same
        (validator-order) pattern every height, so the device-side gather +
        transpose runs once per validator set, not once per call."""
        key = idx.tobytes()
        hit = self._gathered.get(key)
        if hit is not None:
            self._gathered.move_to_end(key)
            return hit
        tab = _gather_transpose(self.niels_rows(), jnp.asarray(idx))
        self._gathered[key] = tab
        # Large batches dispatch in fixed CHUNK slices (ed25519_pallas), so a
        # steady-state 20k-sig commit needs ~5-8 resident chunk patterns.
        while len(self._gathered) > 16:
            self._gathered.popitem(last=False)
        return tab


_KS_LOCK = threading.Lock()
# Level 1: exact pubkey SEQUENCE -> (KeySet, key_idx). Steady-state
# consensus re-verifies the same validator order every height and hits
# this without touching the items.
_KS_CACHE: OrderedDict[bytes, tuple[KeySet, np.ndarray]] = OrderedDict()
_KS_MAX = 8
# Level 2: unique-key-SET digest -> KeySet (the validator-set-content LRU
# the continuous-batching verify service leans on). Coalesced launches
# interleave several callers' items, so the full sequence is novel almost
# every generation while the underlying key set is stable across heights;
# this keeps the expensive device-resident comb tables keyed by SET
# content, so a novel interleaving pays only the O(n) index mapping,
# never a table rebuild. Unique keys are sorted before digesting/building
# so the row order (and digest) is interleaving-independent.
_KS_UNIQ_CACHE: OrderedDict[bytes, KeySet] = OrderedDict()
_KS_UNIQ_MAX = 16


def next_bucket(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _normalize_pubs(pubs: list[bytes]) -> tuple[bytes, np.ndarray]:
    """-> (joined 32-byte-normalized pubkey bytes, (N,) bool size-ok mask)."""
    n = len(pubs)
    ok = np.fromiter((len(p) == ref.PUBKEY_SIZE for p in pubs), dtype=bool, count=n)
    if ok.all():
        return b"".join(pubs), ok
    zero = b"\x00" * 32
    return b"".join(p if len(p) == 32 else zero for p in pubs), ok


def build_keyset(pubs: list[bytes], cache: OrderedDict, lock: threading.Lock,
                 decode_neg, uniq_cache: OrderedDict | None = None,
                 ) -> tuple[KeySet, np.ndarray, np.ndarray]:
    """Shared key-set machinery for any Edwards-comb key type.

    -> (KeySet, key_idx (N,) int32, pub_ok (N,) bool). Two cache levels:
    the exact pubkey SEQUENCE (steady-state consensus hits this every
    height), then the sorted unique-key SET digest (`uniq_cache`) so a
    novel interleaving over known keys — the normal shape of a coalesced
    verify-service launch — reuses the device-resident comb tables and
    only recomputes the item->row mapping. decode_neg: pubkey bytes ->
    extended limbs of -A or None (ed25519 uses RFC 8032 decompression,
    sr25519 ristretto255 decode)."""
    joined, pub_ok = _normalize_pubs(pubs)
    with lock:
        hit = cache.get(joined)
        if hit is not None:
            cache.move_to_end(joined)
            ks, key_idx = hit
            return ks, key_idx, pub_ok

    # dedupe in first-occurrence order, then canonicalize row order by
    # sorting the unique keys: the set digest (and the table row layout)
    # must not depend on how callers' items happened to interleave
    n = len(pubs)
    seen: dict[bytes, int] = {}
    uniq: list[bytes] = []
    key_slot = np.empty(n, dtype=np.int32)
    for i in range(n):
        p = joined[32 * i : 32 * i + 32]
        j = seen.get(p)
        if j is None:
            j = seen[p] = len(uniq)
            uniq.append(p)
        key_slot[i] = j
    order = sorted(range(len(uniq)), key=uniq.__getitem__)
    rank = np.empty(len(uniq), dtype=np.int32)
    for r, j in enumerate(order):
        rank[j] = r
    uniq = [uniq[j] for j in order]
    key_idx = rank[key_slot] if n else key_slot

    ks = None
    set_key = None
    if uniq_cache is not None:
        import hashlib

        set_key = hashlib.sha256(b"".join(uniq)).digest()
        with lock:
            ks = uniq_cache.get(set_key)
            if ks is not None:
                uniq_cache.move_to_end(set_key)
    if ks is None:
        # decompress unique keys, build comb tables on device
        a_neg = np.broadcast_to(ed.IDENTITY_LIMBS, (len(uniq), 4, 20)).copy()
        valid = np.zeros((max(_round_up(len(uniq), KEY_TILE), KEY_TILE),),
                         dtype=bool)
        for j, p in enumerate(uniq):
            neg = decode_neg(p)
            if neg is not None:
                a_neg[j] = neg
                valid[j] = True
        tab_ext = _build_comb_tables_tiled(a_neg)
        ks = KeySet(len(uniq), valid, tab_ext, key_idx)
    with lock:
        cache[joined] = (ks, key_idx)
        while len(cache) > _KS_MAX:
            cache.popitem(last=False)
        if uniq_cache is not None:
            uniq_cache[set_key] = ks
            while len(uniq_cache) > _KS_UNIQ_MAX:
                uniq_cache.popitem(last=False)
    return ks, key_idx, pub_ok


def get_keyset(pubs: list[bytes]) -> tuple[KeySet, np.ndarray, np.ndarray]:
    return build_keyset(pubs, _KS_CACHE, _KS_LOCK, _decompress_neg,
                        uniq_cache=_KS_UNIQ_CACHE)


# ---------------------------------------------------------------------------
# Host-side preparation (vectorized)
# ---------------------------------------------------------------------------

_BIT_W = (1 << np.arange(13, dtype=np.int64)).astype(np.int32)


def _r_to_limbs(r32: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(N, 32) uint8 R bytes -> ((N, 20) raw y limbs, (N,) sign bits)."""
    bits = np.unpackbits(r32, axis=1, bitorder="little")  # (N, 256)
    sign = bits[:, 255].astype(np.int32)
    y_bits = bits[:, :255].astype(np.int32)
    y_bits = np.concatenate(
        [y_bits, np.zeros((y_bits.shape[0], 5), dtype=np.int32)], axis=1
    )  # pad to 260
    limbs = y_bits.reshape(-1, 20, 13) @ _BIT_W
    return limbs.astype(np.int32), sign


def prepare_scalars(items, pub_ok: np.ndarray, windows: bool = True,
                    reduce: bool = True, host_hash: bool = True):
    """Vectorized per-signature prep: scalars, R bytes, validity.

    items: [(pub, msg, sig)]; pub_ok from get_keyset. Returns dict of numpy
    arrays sized to len(items) (unpadded). With windows=False (the Pallas
    path) the comb windows are left to the device and only raw h32/s32
    scalars are produced -- 40% less H2D payload. With reduce=False the
    mod-L reduction is ALSO left to the device: the dict carries the raw
    (N, 64) SHA-512 digests as "h64" and no "h32". With host_hash=False
    even the SHA-512 stays off host: no "h64"; the dict carries "pubs32"
    so the caller can pack R||A||M for the device hasher
    (ops/sha512_jax)."""
    n = len(items)
    sig_ok = np.fromiter(
        (len(it[2]) == ref.SIGNATURE_SIZE for it in items), dtype=bool, count=n
    )
    if sig_ok.all():
        sigs = np.frombuffer(b"".join(it[2] for it in items), dtype=np.uint8)
    else:
        zero = b"\x00" * 64
        sigs = np.frombuffer(
            b"".join(it[2] if len(it[2]) == 64 else zero for it in items),
            dtype=np.uint8,
        )
    sigs = sigs.reshape(n, 64)
    r32 = np.ascontiguousarray(sigs[:, :32])
    s32 = np.ascontiguousarray(sigs[:, 32:])

    pubs32, _ = _normalize_pubs([it[0] for it in items])
    pubs_arr = np.frombuffer(pubs32, dtype=np.uint8).reshape(n, 32)

    s_lt = sc.lt_l(s32)
    valid = sig_ok & s_lt & pub_ok
    out = dict(s32=s32, r32=r32, valid=valid)
    if not host_hash:
        out["pubs32"] = np.ascontiguousarray(pubs_arr)
        return out
    digests = chash.sha512_rab(r32, np.ascontiguousarray(pubs_arr),
                               [it[1] for it in items])
    if not reduce:
        out["h64"] = digests
        return out
    h32 = sc.reduce_mod_l(digests)
    out["h32"] = h32
    if windows:
        out["h_win"] = sc.comb_windows(h32)
        out["s_win"] = sc.comb_windows(s32)
    return out




def _jnp_args(s: dict, n: int, nb: int) -> dict:
    """prepare_scalars output -> padded (N-major, int32) args for the jnp
    kernel: h_win, s_win, r_y, r_sign, valid."""
    r_y, r_sign = _r_to_limbs(s["r32"])
    out = {}
    for k, v in (("h_win", s["h_win"].astype(np.int32)),
                 ("s_win", s["s_win"].astype(np.int32)),
                 ("r_y", r_y), ("r_sign", r_sign), ("valid", s["valid"])):
        pad = np.zeros((nb,) + v.shape[1:], dtype=v.dtype)
        pad[:n] = v
        out[k] = pad
    return out


def prepare(items):
    """Padded full-batch prep for the jnp kernel (compat path used by the
    multi-chip shard harness): returns (dict incl. gathered per-item comb
    tables, n)."""
    n = len(items)
    nb = next_bucket(n)
    ks, key_idx, pub_ok = get_keyset([it[0] for it in items])
    # Keys that failed decompression sit in the table as the identity point;
    # without this mask a forged (R = compress([s]B), s) pair would verify
    # under any off-curve pubkey (the scalar path rejects these).
    pub_ok = pub_ok & ks.valid[key_idx]
    s = prepare_scalars(items, pub_ok)
    idx = np.zeros((nb,), dtype=np.int32)
    idx[:n] = key_idx
    out = _jnp_args(s, n, nb)
    out["tab"] = np.asarray(jnp.take(ks.tab_ext, jnp.asarray(idx), axis=0))
    return out, n


def _use_pallas() -> bool:
    import os

    mode = os.environ.get("TM_TPU_ED25519_KERNEL", "auto")
    if mode == "pallas":
        return True
    if mode == "jnp":
        return False
    # Pallas TPU lowering only; "axon" is this image's TPU plugin name.
    return jax.default_backend() in ("tpu", "axon")


# ---------------------------------------------------------------------------
# Host (CPU) crossover: below this batch size the C verifier (ops/chost,
# Pippenger RLC batch) wins because a kernel flush pays the host<->device
# sync floor (~90 ms through this host's TPU tunnel). The adaptive value is
# measured at warmup (VERDICT r4 item 1a: measured crossover, not a static
# batch_min); until calibrated a conservative default keeps sub-2k batches
# off the link.
# ---------------------------------------------------------------------------

_HOST_CAL: dict = {"crossover": None, "floor_ms": None, "host_us": None}
_HOST_CAL_LOCK = threading.Lock()
HOST_CROSSOVER_DEFAULT = 2048


def host_crossover() -> int:
    """Current batch-size threshold below which verification runs on host.
    TM_TPU_HOST_CROSSOVER overrides (0 disables the host path)."""
    v = os.environ.get("TM_TPU_HOST_CROSSOVER")
    if v is not None:
        return int(v)
    c = _HOST_CAL["crossover"]
    return c if c is not None else HOST_CROSSOVER_DEFAULT


def calibrate_host_crossover(device_marginal_us: float = 2.5) -> int:
    """Measure the sync floor and the host RLC rate, set the crossover to
    floor / (host_us - device_us) clamped to [256, 16384]. One-time cost:
    ~0.5 s (64 python signs + 3 tiny device round trips). Idempotent."""
    from tendermint_tpu.ops import chost

    with _HOST_CAL_LOCK:
        if _HOST_CAL["crossover"] is not None:
            return _HOST_CAL["crossover"]
        # ensure_available: calibration runs in the warmup background
        # thread, the designated place to pay the gcc build once.
        if not chost.ensure_available():
            _HOST_CAL["crossover"] = 0
            return 0
        import time as _t

        # host RLC rate on 256 items (64 unique sigs tiled; the A-decompress
        # cache makes the tiling realistic for steady-state consensus)
        priv = ref.gen_priv_key(b"\x51" * 32)
        base = [(priv.pub_key().data, b"cal%d" % i,
                 ref.sign(priv.data, b"cal%d" % i)) for i in range(64)]
        items = base * 4
        joined, pub_ok = _normalize_pubs([it[0] for it in items])
        s = prepare_scalars(items, pub_ok, windows=False)
        pubs_arr = np.frombuffer(joined, dtype=np.uint8).reshape(-1, 32)
        args = (pubs_arr, s["h32"], s["s32"], s["r32"], s["valid"])
        out = chost.ed25519_verify(*args, mode=1)
        if not out.all():  # self-check failed: never route here
            _HOST_CAL["crossover"] = 0
            return 0
        t0 = _t.monotonic()
        chost.ed25519_verify(*args, mode=1)
        host_us = (_t.monotonic() - t0) * 1e6 / len(items)
        # sync floor of one flush round trip
        tiny = jax.jit(lambda a: a * 2)
        floor_ms = min(
            _measure_once(lambda: np.asarray(tiny(jnp.ones((1,), jnp.int32))))
            for _ in range(3))
        margin = max(host_us - device_marginal_us, 1.0)
        cross = int(min(max(floor_ms * 1e3 / margin, 256), 16384))
        _HOST_CAL.update(crossover=cross, floor_ms=floor_ms, host_us=host_us)
        return cross


def _measure_once(fn) -> float:
    import time as _t

    t0 = _t.monotonic()
    fn()
    return (_t.monotonic() - t0) * 1e3


def _dispatch_host(items, n):
    """Synchronous host-path dispatch: C serial/RLC verify (ops/chost).
    Returns the (device_out=None, finish) pair of the dispatch contract."""
    from tendermint_tpu.ops import chost

    joined, pub_ok = _normalize_pubs([it[0] for it in items])
    s = prepare_scalars(items, pub_ok, windows=False)
    pubs_arr = np.frombuffer(joined, dtype=np.uint8).reshape(n, 32)
    bitmap = chost.ed25519_verify(pubs_arr, s["h32"], s["s32"], s["r32"],
                                  s["valid"])
    return None, lambda _unused: bitmap


def _scalar_fallback_bitmap(items) -> np.ndarray:
    """Pure-Python serial re-verification: the degradation floor that needs
    neither the device nor the C library (used while the C build is in
    flight and as the last rung of the circuit-breaker fallback)."""
    return np.fromiter((ref.verify(p, m, s) for (p, m, s) in items),
                       dtype=bool, count=len(items))


def _host_fallback(items, n):
    """(device_out=None, finish) via the best available host path: the C
    verifier when loaded, else the pure-Python scalar loop."""
    from tendermint_tpu.ops import chost

    if chost.available():
        return _dispatch_host(items, n)
    bitmap = _scalar_fallback_bitmap(items)
    return None, lambda _unused: bitmap


def _dispatch_device(items, n: int, multichip: bool):
    """The accelerator route proper: comb tables + Pallas / shard_map / jnp
    kernel dispatch. Raises on device failure (injected or real); the
    circuit breaker in dispatch_batch owns the fallback. The fault site
    fires in dispatch_batch, NOT here: the breaker probe also runs this
    function, and probe timing must never consume the deterministic
    consensus-path hit indices of ops.ed25519.device."""
    ks, key_idx, pub_ok = get_keyset([it[0] for it in items])
    # Non-decompressable keys get an identity comb table; they must be
    # rejected here, exactly as the scalar path's _decompress(pub) is None.
    pub_ok = pub_ok & ks.valid[key_idx]
    if multichip:
        # Multi-chip: shard the signature axis over the device mesh
        # (BASELINE.json north_star: validator sets sharded across TPU
        # cores, pass/fail bitmap all-reduced). Routing policy and knobs
        # (TM_TPU_SHARD / TM_TPU_SHARD_MIN) live in batch_shard.should_shard;
        # batches below the threshold stay on the single-device path.
        from tendermint_tpu.parallel import batch_shard

        dev = batch_shard.dispatch_batch_sharded(ks, key_idx, items, pub_ok)
        _start_host_copy(dev)
        return dev, lambda v: np.asarray(v)[:n].astype(bool)
    if _use_pallas():
        # Prep is done chunk-by-chunk inside the pipelined path so device
        # compute overlaps host prep of the next chunk.
        from tendermint_tpu.ops import ed25519_pallas

        dev = ed25519_pallas.pack_bitmap(
            ed25519_pallas.dispatch_items_pipelined(ks, key_idx, items, pub_ok))
        _start_host_copy(dev)
        return dev, lambda v: ed25519_pallas.unpack_bitmap(np.asarray(v), n)
    s = prepare_scalars(items, pub_ok, windows=True)

    # Fixed-tile chunking: every batch runs through the one JNP_TILE-shaped
    # executable, so no batch size ever triggers a fresh XLA compile.
    nb = max(_round_up(n, JNP_TILE), JNP_TILE)
    idx = np.zeros((nb,), dtype=np.int32)
    idx[:n] = key_idx
    padded = _jnp_args(s, n, nb)
    outs = []
    for off in range(0, nb, JNP_TILE):
        tab = jnp.take(ks.tab_ext, jnp.asarray(idx[off : off + JNP_TILE]), axis=0)
        outs.append(_jnp_kernel(tab, **{
            k: jnp.asarray(v[off : off + JNP_TILE]) for k, v in padded.items()
        }))
    ok = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    _start_host_copy(ok)
    return ok, lambda v: np.asarray(v)[:n].astype(bool)


def _device_probe() -> bool:
    """Circuit-breaker probe: one real signature through the device route.
    Runs in the breaker's background thread, never on the consensus path.
    Fires its own fault site (keep a dead-device simulation dead with
    TMTPU_FAULTS="ops.ed25519.device:raise,ops.ed25519.probe:raise")."""
    faults.fire("ops.ed25519.probe")
    priv = ref.gen_priv_key(b"\x7b" * 32)
    items = [(priv.pub_key().data, b"breaker-probe",
              ref.sign(priv.data, b"breaker-probe"))]
    dev, finish = _dispatch_device(items, 1, multichip=False)
    return bool(np.all(finish(jax.device_get(dev))))


BREAKER = _cbreaker.CircuitBreaker("ed25519-device", probe=_device_probe)


def dispatch_batch(items: list[tuple[bytes, bytes, bytes]],
                   force_device: bool = False):
    """Async batched verify of [(pub, msg, sig)]: all host prep + device
    dispatches are issued, nothing is fetched. Returns (device_out, finish)
    where `finish(jax.device_get(device_out))` -> (len(items),) bool. Lets
    callers (MixedBatchVerifier) overlap the fetch latency of several
    kernels in ONE device_get -- the tunnel round trip is latency-bound, so
    two sequential fetches cost two floors, one batched fetch costs one.

    Routes to the C host verifier below the measured crossover (ops/chost),
    else the fused Pallas kernel on TPU (ops/ed25519_pallas), the shard_map
    multi-device path when a mesh is present, or the pure-jnp CPU fallback.
    force_device=True skips the host route (kernel warmup, kernel tests).

    The device route sits behind a circuit breaker (ops/breaker): a device
    dispatch failure is re-verified on the host within the same call, the
    circuit opens, and later batches go straight to the host until a
    background probe re-closes it -- consensus keeps committing with a dead
    accelerator. While open, even force_device callers are degraded."""
    if not items:
        return None, lambda _: np.zeros((0,), dtype=bool)
    from tendermint_tpu.parallel import batch_shard

    n = len(items)
    multichip = batch_shard.should_shard(n)
    if not multichip and not force_device and n < host_crossover():
        # Below the measured crossover a kernel flush loses to the CPU: the
        # sync floor alone exceeds the C verifier's whole runtime. No device
        # tables are built on this path (host verification is self-contained).
        from tendermint_tpu.ops import chost

        if chost.available():
            return _dispatch_host(items, n)
        if chost.building():
            # The gcc build is in flight: serial Python (~2 ms/sig, bounded
            # by the build window) beats the alternative -- on a cold
            # process the device route here means a fresh XLA compile, an
            # order of magnitude worse than scalar-verifying these batches.
            # (_host_fallback resolves to the scalar loop while building.)
            return _host_fallback(items, n)
    def _device():
        faults.fire("ops.ed25519.device")
        return _dispatch_device(items, n, multichip)

    return _cbreaker.guarded_dispatch(
        BREAKER, _device, lambda: _host_fallback(items, n))


def _start_host_copy(dev) -> None:
    """Begin the D2H transfer NOW: over this host's tunnel a device_get
    issued after the command stream drains pays a fresh ~90 ms round trip
    even when the result has long been computed; a copy started at dispatch
    rides the active stream and makes the later fetch ~free (measured:
    fetch 0.2 ms vs 88 ms after 150 ms of host work)."""
    try:
        dev.copy_to_host_async()
    except (AttributeError, RuntimeError):
        pass


def verify_batch(items: list[tuple[bytes, bytes, bytes]],
                 force_device: bool = False) -> np.ndarray:
    """Batched verify of [(pub, msg, sig)]; returns (len(items),) bool."""
    dev, finish = dispatch_batch(items, force_device=force_device)
    return _cbreaker.guarded_fetch(
        BREAKER, dev, finish, lambda: _host_fallback(items, len(items)))
