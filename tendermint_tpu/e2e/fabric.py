"""Scenario fabric: a 50-100+ node in-process mesh with configurable
topology, validator churn, and enforced resource budgets (ROADMAP item 5;
reference: test/e2e/ builds testnets of processes — this fabric builds them
out of in-process nodes stitched over socketpairs, the seam the nemesis
link plane cuts).

The fabric exists because BFT bugs surface under scale, churn, and time:
the 2-4 node harnesses in tests/test_nemesis.py and tests/test_overload.py
prove mechanisms, not behavior at 50 validators. A :class:`Cluster` owns N
in-process :class:`~tendermint_tpu.node.node.Node` objects peered over raw
``socket.socketpair()`` links (no TCP, no `cryptography` dependency; every
nemesis choke point lives in MConnection above the socket), wired in one of
three topologies:

* ``full`` — every pair linked. O(n^2) links: fine to ~10 nodes, ruinous
  at 50 (2450 fds, ~17k threads before the gossip-thread merge).
* ``k-regular:<k>[:<seed>]`` — a ring plus seeded random chord matchings
  until every node has degree ~k. Diameter ~log n; the default for big
  clusters.
* ``hub-spoke:<h>`` — h fully-meshed hubs, every spoke linked to all hubs.
  Diameter 2 at the cost of hot hubs.

**Churn is a first-class action.** ``join_node()`` adds a node to a LIVE
cluster (fast-sync catchup from genesis, or statesync bootstrap through a
serving node's RPC + snapshots), ``promote()`` drives a voting-power change
through the kvstore ``val:`` tx -> ABCI ``validator_updates`` ->
``state/execution.py update_state`` path so the joiner starts voting two
heights later, and ``remove_node()`` / ``restart_node()`` take a validator
out mid-height. Evidence submitted mid-churn rides the normal evidence
reactor.

**Resource budgets are enforced, not hoped for.** One process hosting 50+
nodes lives or dies on per-peer thread count and per-link fd count, so the
fabric accounts for both: `PER_PEER_THREADS`/`NODE_BASE_THREADS` encode the
claimed per-node footprint (the consensus reactor's three gossip threads
were merged into one for exactly this budget), and
:meth:`Cluster.assert_resource_budget` fails loudly when the live process
exceeds what the topology predicts — a regression that quietly adds a
per-peer thread breaks the budget test before it breaks a 100-node soak.

Verification cost is shared through the existing seams: one process-wide
BatchVerifier registry and one signature cache (crypto/sigcache.py), so a
vote gossiped to 50 nodes pays ONE verification, not 50.

See docs/SOAK.md for the soak driver that schedules perturbations against
a cluster, and docs/NEMESIS.md for the link plane it drives.
"""

from __future__ import annotations

import itertools
import os
import random
import socket
import threading
import time

from tendermint_tpu.utils import nemesis

# --- resource budget constants ----------------------------------------------
#
# The per-node thread bill, by construction (asserted live by
# Cluster.assert_resource_budget; tests/test_fabric.py pins the arithmetic):
#
#   per peer (one side of one link):
#     2  MConnection send + recv routines
#     1  consensus gossip routine (data+votes+maj23 merged; was 3 pre-fabric)
#     1  evidence broadcast routine
#     1  mempool gossip routine        (only when mempool broadcast is on)
#   per node:
#     1  switch reconnect loop
#     1  consensus receive routine
#     1  watchdog
#     1  mempool tx-available notifier (only when mempool broadcast is on)
#     +  transient: statesync/fast-sync threads during a join, timers
#
# NODE_BASE_THREADS carries one slot of transient slack per node on top of
# the steady-state three/four. If either constant has to grow, the PR that
# grows it is spending the fabric's scale budget and should say so.

PER_PEER_THREADS = 4
PER_PEER_THREADS_MEMPOOL = 1
NODE_BASE_THREADS = 5
# Lazy ingest-coalescer executor (mempool/ingest.py, docs/INGEST.md): one
# per node, spawned on the node's first front-door tx — submit_tx and every
# gossip delivery route through it, so a loaded cluster holds one each.
# Spending the scale budget deliberately: it buys one batched CheckTx
# dispatch per micro-batch instead of one app round trip per tx.
NODE_THREADS_INGEST = 1
FDS_PER_LINK = 2       # one socketpair end per side
FDS_PER_NODE = 6       # WAL + sqlite handles (durable) + metrics/rpc slack


class PlainConn:
    """SecretConnection surface over a raw socket — the image lacks the
    optional `cryptography` package, so in-process nodes are stitched
    together unencrypted. Every nemesis choke point lives in MConnection
    (framing, channels, fault sites), which runs unchanged on top."""

    def __init__(self, sock):
        self._s = sock

    def write(self, b):
        self._s.sendall(b)

    def read(self, n):
        try:
            return self._s.recv(n)
        except OSError:
            return b""

    def close(self):
        try:
            self._s.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._s.close()
        except OSError:
            pass


def link_nodes(a, b) -> None:
    """Register a<->b as real peers of each other over a socketpair (the
    switch's own _add_peer: real Peer, real MConnection, all reactors)."""
    sa, sb = socket.socketpair()
    a.switch._add_peer(PlainConn(sa), b.transport.node_info, outbound=True)
    b.switch._add_peer(PlainConn(sb), a.transport.node_info, outbound=False)


# --- topology ----------------------------------------------------------------


def full_mesh_edges(n: int) -> list[tuple[int, int]]:
    return [(j, i) for i in range(n) for j in range(i)]


def k_regular_edges(n: int, k: int, seed: int = 0) -> list[tuple[int, int]]:
    """Ring + seeded random chord matchings to degree ~k. Deterministic in
    (n, k, seed); every node ends within one of degree k, the graph is
    connected (the ring guarantees it), and diameter is ~log n."""
    if n < 3 or k < 2:
        return full_mesh_edges(n)
    k = min(k, n - 1)
    edges = {(i, (i + 1) % n) if i < (i + 1) % n else ((i + 1) % n, i)
             for i in range(n)}
    rng = random.Random(f"fabric:{seed}:{n}:{k}")
    degree = {i: 2 for i in range(n)}
    # chord rounds: pair up nodes still under degree k, skipping self loops
    # and duplicates; a bounded number of passes keeps this total even when
    # parity leaves one node short
    for _ in range(4 * k):
        under = [i for i in range(n) if degree[i] < k]
        if len(under) < 2:
            break
        rng.shuffle(under)
        for a, b in zip(under[0::2], under[1::2]):
            e = (a, b) if a < b else (b, a)
            if a == b or e in edges:
                continue
            edges.add(e)
            degree[a] += 1
            degree[b] += 1
    return sorted(edges)


def hub_spoke_edges(n: int, hubs: int) -> list[tuple[int, int]]:
    """Nodes [0, hubs) are hubs (fully meshed); every spoke links to all
    hubs. Diameter 2: the scale topology when propagation latency matters
    more than hub thread count."""
    hubs = max(1, min(hubs, n))
    edges = [(j, i) for i in range(hubs) for j in range(i)]
    edges += [(h, s) for s in range(hubs, n) for h in range(hubs)]
    return sorted(edges)


def topology_edges(spec: str, n: int) -> list[tuple[int, int]]:
    """Parse a topology spec: ``full``, ``k-regular:<k>[:<seed>]``, or
    ``hub-spoke:<h>``."""
    parts = spec.split(":")
    kind = parts[0]
    if kind == "full":
        return full_mesh_edges(n)
    if kind == "k-regular":
        k = int(parts[1]) if len(parts) > 1 else 6
        seed = int(parts[2]) if len(parts) > 2 else 0
        return k_regular_edges(n, k, seed)
    if kind == "hub-spoke":
        return hub_spoke_edges(n, int(parts[1]) if len(parts) > 1 else 2)
    raise ValueError(f"unknown topology {spec!r} "
                     "(want full, k-regular:<k>[:<seed>], or hub-spoke:<h>)")


# --- the cluster -------------------------------------------------------------


def _priv_seed(tag: int, i: int) -> bytes:
    """32 deterministic key-seed bytes; safe past i=255 (a 100+ node
    cluster outgrows the bytes([x + i]) idiom of the small harnesses)."""
    return (bytes([tag]) + i.to_bytes(4, "big")).ljust(32, b"\xa7")


_GENERATION = itertools.count(1)


class FabricNode:
    """One in-process node plus the bookkeeping the cluster needs."""

    def __init__(self, idx: int, node, priv, home: str, joined_via: str = ""):
        self.idx = idx
        self.node = node
        self.priv = priv          # validator MockPV key (may be 0-power)
        self.home = home
        self.joined_via = joined_via  # "", "fastsync", "statesync"
        self.links: set[int] = set()
        # Monotonic across every node this process ever builds: restart
        # detection for the soak auditor. id(node) alone is unsafe — the
        # old Node gets garbage-collected and CPython can hand the SAME
        # address to its replacement, which would silently skip the
        # restarted node's full-prefix re-verification.
        self.generation = next(_GENERATION)

    @property
    def id(self) -> str:
        return self.node.node_key.id()

    @property
    def height(self) -> int:
        return self.node.block_store.height


class Cluster:
    """N in-process nodes over socketpairs with a shared genesis.

    The constructor only prepares configuration; :meth:`start` boots the
    nodes and stitches the topology. ``n_validators`` (default: all nodes)
    puts only the first ``n_validators`` nodes in the genesis validator
    set — extra nodes are full nodes (and churn candidates)."""

    def __init__(self, root: str, n: int, topology: str = "full",
                 n_validators: int | None = None, power: int = 10,
                 chain_id: str = "fabric-chain", mempool_broadcast: bool = True,
                 durable: bool = False, snapshot_interval: int = 0,
                 rpc_node: int = -1, metrics_node: int = -1, tweak=None,
                 trace: bool = False, powers: list[int] | None = None,
                 rpc_nodes: tuple = (), byzantine: dict | None = None,
                 logger=None):
        self.root = str(root)
        self.n_initial = n
        self.topology = topology
        self.n_validators = n if n_validators is None else n_validators
        self.power = power
        # per-validator genesis powers (index-aligned, overrides the
        # uniform `power`): the byzantine attack cookbook needs uneven
        # trajectories — e.g. a posterior-corruption lunatic that HELD
        # >= 1/3 at an old height but holds < 1/3 live (docs/BYZANTINE.md)
        self.powers = list(powers) if powers is not None else None
        # byzantine is a first-class fabric role: {idx: behavior spec}
        # (consensus/misbehavior.py grammar), installed at start() behind
        # a strict < 1/3 aggregate-power guard; self.byzantine tracks the
        # role for the auditors (honest-prefix fork audit, quorum math)
        self._byzantine_specs = dict(byzantine) if byzantine else {}
        self.byzantine: set[int] = set()
        self.chain_id = chain_id
        self.rpc_nodes = tuple(rpc_nodes)
        self.mempool_broadcast = mempool_broadcast
        self.durable = durable
        self.snapshot_interval = snapshot_interval
        self.rpc_node = rpc_node
        self.metrics_node = metrics_node
        self.tweak = tweak
        # trace=True enables every node's flight recorder (utils/trace.py)
        # at build time — the soak auditor's stall annotations need the
        # per-node span ring; TMTPU_TRACE=1 does the same via the env
        self.trace = trace
        self.logger = logger
        self.nodes: dict[int, FabricNode] = {}
        # hard-killed incarnations awaiting reboot(): idx -> (priv, links)
        # — the durable home under root/node<idx> is the crash artifact
        self._crashed: dict[int, tuple] = {}
        self._next_idx = 0
        self._genesis = None
        self._privs: list = []
        self._baseline_threads = 0
        self._baseline_fds = 0
        self._lock = threading.Lock()

    # --- construction -------------------------------------------------------

    def _make_genesis(self):
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
        from tendermint_tpu.types.ttime import Time

        self._privs = [ed25519.gen_priv_key(_priv_seed(0x11, i))
                       for i in range(self.n_initial)]

        def power_of(i: int) -> int:
            if self.powers is not None and i < len(self.powers):
                return self.powers[i]
            return self.power

        self._genesis = GenesisDoc(
            chain_id=self.chain_id,
            genesis_time=Time(1700009000, 0),
            validators=[GenesisValidator(b"", p.pub_key(), power_of(i))
                        for i, p in enumerate(self._privs[:self.n_validators])],
        )

    def _mk_config(self, idx: int):
        from tendermint_tpu.config.config import test_config

        cfg = test_config()
        cfg.set_root(os.path.join(self.root, f"node{idx}"))
        os.makedirs(cfg.base.root_dir, exist_ok=True)
        cfg.base.fast_sync_mode = False
        cfg.p2p.laddr = ""   # peered via socketpairs
        cfg.p2p.pex = False  # no transport to dial discovered addrs through
        cfg.rpc.laddr = ""
        cfg.tx_index.indexer = "null"  # 1 thread/node the fabric can't spend
        cfg.consensus.wal_path = os.path.join(cfg.base.root_dir, "cs.wal")
        cfg.mempool.broadcast = self.mempool_broadcast
        if self.durable:
            cfg.base.db_backend = "sqlite"
        if idx == self.rpc_node:
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
            cfg.rpc.unsafe = True
        elif idx in self.rpc_nodes:
            # extra RPC listeners (no unsafe routes): the live light-client
            # attack scenario points an out-of-process client at a
            # byzantine primary AND an honest witness over real RPC
            cfg.rpc.laddr = "tcp://127.0.0.1:0"
        if idx == self.metrics_node:
            cfg.instrumentation.prometheus = True
            cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        if self.tweak is not None:
            self.tweak(cfg, idx)
        return cfg

    def _mk_app(self):
        from tendermint_tpu.abci.kvstore import KVStoreApplication

        return KVStoreApplication(snapshot_interval=self.snapshot_interval)

    def _mk_node(self, idx: int, priv, statesync_from: str = "",
                 fast_sync: bool = False, joined_via: str = "") -> FabricNode:
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.node.node import Node
        from tendermint_tpu.p2p.key import NodeKey
        from tendermint_tpu.privval.file_pv import MockPV

        cfg = self._mk_config(idx)
        if fast_sync:
            cfg.base.fast_sync_mode = True
        if statesync_from:
            cfg.base.fast_sync_mode = True
            cfg.statesync.enable = True
            cfg.statesync.rpc_servers = (statesync_from,)
            cfg.statesync.discovery_time_s = 0.5
            cfg.statesync.chunk_request_timeout_s = 5.0
            cfg.statesync.trust_period_s = 10 * 365 * 24 * 3600.0
            seed = self.nodes[min(self.nodes)].node
            meta = seed.block_store.load_block_meta(2)
            if meta is None:
                raise RuntimeError("statesync join needs the cluster at "
                                   "height >= 2 for a trust anchor")
            cfg.statesync.trust_height = 2
            cfg.statesync.trust_hash = meta.block_id.hash.hex()
        node_key = NodeKey(ed25519.gen_priv_key(_priv_seed(0x22, idx)))
        node = Node(cfg, app=self._mk_app(), genesis=self._genesis,
                    priv_validator=MockPV(priv), node_key=node_key,
                    logger=self.logger)
        if self.trace:
            node.tracer.enable()
        return FabricNode(idx, node, priv, cfg.base.root_dir,
                          joined_via=joined_via)

    def start(self) -> None:
        """Boot all initial nodes and stitch the topology."""
        self._baseline_threads = threading.active_count()
        self._baseline_fds = _open_fds()
        if self._genesis is None:
            self._make_genesis()
        for i in range(self.n_initial):
            fn = self._mk_node(i, self._privs[i])
            self.nodes[i] = fn
            fn.node.start()
        self._next_idx = self.n_initial
        for i, j in topology_edges(self.topology, self.n_initial):
            self.link(i, j)
        for idx, spec in sorted(self._byzantine_specs.items()):
            self.install_byzantine(idx, spec)

    def stop(self) -> None:
        for fn in list(self.nodes.values()):
            try:
                fn.node.stop()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        self.nodes.clear()
        self._crashed.clear()

    # --- links --------------------------------------------------------------

    def link(self, i: int, j: int) -> None:
        link_nodes(self.nodes[i].node, self.nodes[j].node)
        self.nodes[i].links.add(j)
        self.nodes[j].links.add(i)

    def unlink(self, i: int, j: int) -> None:
        a, b = self.nodes.get(i), self.nodes.get(j)
        if a is not None and b is not None:
            a.node.switch.stop_peer_by_id(b.id, "fabric unlink")
            b.node.switch.stop_peer_by_id(a.id, "fabric unlink")
        if a is not None:
            a.links.discard(j)
        if b is not None:
            b.links.discard(i)

    def relink_missing(self, timeout: float = 20.0) -> None:
        """Re-establish severed links after a heal (the socketpair harness
        has no transport to redial through, so the relink is explicit —
        a real deployment's persistent-peer redial does this)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            missing = [(i, j) for i, fn in sorted(self.nodes.items())
                       for j in sorted(fn.links)
                       if j > i and j in self.nodes
                       and (self.nodes[j].id not in fn.node.switch.peers
                            or fn.id not in self.nodes[j].node.switch.peers)]
            if not missing:
                return
            for i, j in missing:
                self.nodes[i].node.switch.stop_peer_by_id(
                    self.nodes[j].id, "relink")
                self.nodes[j].node.switch.stop_peer_by_id(
                    self.nodes[i].id, "relink")
                try:
                    link_nodes(self.nodes[i].node, self.nodes[j].node)
                except Exception:  # noqa: BLE001 - teardown still in flight
                    pass
            time.sleep(0.1)
        raise AssertionError("fabric relink failed after heal")

    # --- nemesis handles (indices in, node ids out) -------------------------

    def node_id(self, i: int) -> str:
        return self.nodes[i].id

    def rpc_url(self, i: int) -> str:
        """http base URL of a node's RPC listener (needs rpc_node or
        rpc_nodes to have included ``i`` at construction)."""
        rpc = self.nodes[i].node.rpc_server
        if rpc is None:
            raise RuntimeError(f"node {i} has no RPC listener "
                               "(pass rpc_node/rpc_nodes)")
        return "http://" + rpc.laddr.split("://", 1)[1]

    def partition(self, groups: list[list[int]]) -> None:
        nemesis.partition([[self.node_id(i) for i in g if i in self.nodes]
                           for g in groups])

    def heal(self, relink: bool = True) -> None:
        nemesis.heal()
        if relink:
            self.relink_missing()

    def add_link_rule(self, src: int | str, dst: int | str,
                      action_spec: str):
        """Directed link rule with fabric indices: ``add_link_rule(0, 3,
        "drop%0.5#0x22")``; ``"*"`` passes through as the wildcard.
        Returns the installed LinkRule so a scheduler can expire exactly
        this rule later (``nemesis.remove_link``)."""
        s = src if isinstance(src, str) else self.node_id(src)
        d = dst if isinstance(dst, str) else self.node_id(dst)
        return nemesis.add_link(f"{s}>{d}:{action_spec}")

    # --- heights / safety ---------------------------------------------------

    def heights(self) -> dict[int, int]:
        return {i: fn.height for i, fn in sorted(self.nodes.items())}

    def min_height(self, among: list[int] | None = None) -> int:
        hs = [fn.height for i, fn in self.nodes.items()
              if among is None or i in among]
        return min(hs) if hs else 0

    def max_height(self) -> int:
        return max((fn.height for fn in self.nodes.values()), default=0)

    def wait_min_height(self, target: int, timeout: float,
                        among: list[int] | None = None,
                        poll: float = 0.1) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.min_height(among) >= target:
                return True
            time.sleep(poll)
        return False

    def block_hash(self, i: int, h: int) -> bytes | None:
        from tendermint_tpu.store.envelope import CorruptedStoreError

        try:
            meta = self.nodes[i].node.block_store.load_block_meta(h)
        except CorruptedStoreError:
            # quarantined + repair scheduled by the node's own hook; the
            # auditor re-reads this height next sweep (a repaired row
            # re-enters the agreement check, rot is never "agreed")
            return None
        return None if meta is None else meta.block_id.hash

    def audit_agreement(self, min_height: int = 1,
                        include_byzantine: bool = False) -> int:
        """Full-prefix fork audit: every committed height on every HONEST
        node must carry one block hash cluster-wide (safety under
        byzantium is a promise about the honest prefix; a byzantine node's
        store is its own problem — pass include_byzantine=True to audit it
        anyway). Returns heights audited; raises AssertionError with the
        per-node map on any fork."""
        audited = 0
        skip = set() if include_byzantine else self.byzantine
        for h in range(min_height, self.max_height() + 1):
            hashes = {}
            for i in sorted(self.nodes):
                if i in skip:
                    continue
                bh = self.block_hash(i, h)
                if bh is not None:
                    hashes[i] = bh
            if len(hashes) >= 2:
                audited += 1
                if len(set(hashes.values())) != 1:
                    raise AssertionError(
                        f"fork at height {h}: "
                        f"{ {i: v.hex()[:16] for i, v in hashes.items()} }")
        return audited

    # --- churn --------------------------------------------------------------

    def join_node(self, statesync: bool = False, link_to: list[int] | None = None,
                  links: int = 3) -> int:
        """Add a fresh node to the LIVE cluster and return its index.

        ``statesync=True`` bootstraps through the ``rpc_node``'s RPC (needs
        ``snapshot_interval`` > 0 on the serving apps and the chain past the
        trust anchor); otherwise the node fast-syncs from genesis. Either
        way it lands in consensus as a non-validator until :meth:`promote`
        gives it power."""
        from tendermint_tpu.crypto import ed25519

        idx = self._next_idx
        self._next_idx += 1
        statesync_from = ""
        if statesync:
            if self.rpc_node < 0 or self.rpc_node not in self.nodes:
                raise RuntimeError("statesync join needs rpc_node >= 0 (a "
                                   "serving node with an RPC listener) and "
                                   "snapshot_interval > 0 on the apps")
            rpc = self.nodes[self.rpc_node].node.rpc_server
            if rpc is None:
                raise RuntimeError("statesync join needs rpc_node >= 0")
            statesync_from = "http://" + rpc.laddr.split("://", 1)[1]
        priv = ed25519.gen_priv_key(_priv_seed(0x11, idx))
        fn = self._mk_node(idx, priv, statesync_from=statesync_from,
                           fast_sync=not statesync,
                           joined_via="statesync" if statesync else "fastsync")
        with self._lock:
            self.nodes[idx] = fn
        fn.node.start()
        targets = (link_to if link_to is not None else
                   sorted(self.nodes)[:links])
        for j in targets:
            if j != idx and j in self.nodes:
                self.link(idx, j)
        return idx

    def remove_node(self, idx: int) -> None:
        """Take a node out mid-height: unlink everywhere, then stop it.
        O(degree), not O(cluster)."""
        fn = self.nodes.get(idx)
        if fn is None:
            return
        for j in sorted(fn.links):
            self.unlink(idx, j)
        with self._lock:
            self.nodes.pop(idx, None)
        fn.node.stop()

    def restart_node(self, idx: int, links: int = 3) -> int:
        """Stop a node and boot a replacement with the same validator key
        (same home when durable; a fresh fast-sync from genesis when the
        stores were memdb). Returns the node's (unchanged) index."""
        fn = self.nodes.get(idx)
        if fn is None:
            raise KeyError(idx)
        old_links = sorted(fn.links) or sorted(self.nodes)[:links]
        priv = fn.priv
        self.remove_node(idx)
        if not self.durable:
            # memdb stores die with the node but the WAL is a FILE in the
            # reused home: a fresh-state replacement replaying the previous
            # incarnation's #ENDHEIGHT markers is a hard consensus error
            # (_catchup_replay refuses a WAL ahead of the state store)
            try:
                os.remove(os.path.join(fn.home, "cs.wal"))
            except OSError:
                pass
        nfn = self._mk_node(idx, priv, fast_sync=not self.durable,
                            joined_via="restart")
        with self._lock:
            self.nodes[idx] = nfn
        nfn.node.start()
        for j in old_links:
            if j != idx and j in self.nodes:
                self.link(idx, j)
        return idx

    def hard_kill(self, idx: int, tear: str | None = None,
                  seed: int = 0) -> None:
        """Power-cut a node (docs/SOAK.md ``crash`` action): sever every
        link, abandon the incarnation via :meth:`Node.abort` — no flushes,
        no WAL close, no sink drain — and leave the durable home exactly
        as the crash instant left it. ``tear="torn"|"partial"`` then cuts
        the WAL's final frame on the abandoned home
        (``faults.tear_wal_tail``), the state a power cut mid-append
        leaves. :meth:`reboot` boots a new incarnation from the home."""
        fn = self.nodes.get(idx)
        if fn is None:
            raise KeyError(idx)
        if not self.durable:
            raise RuntimeError(
                "hard_kill needs Cluster(durable=True): a memdb home dies "
                "with the incarnation, leaving reboot() nothing to recover")
        old_links = sorted(fn.links)
        for j in old_links:
            self.unlink(idx, j)
        with self._lock:
            self.nodes.pop(idx, None)
        fn.node.abort()
        if tear:
            from tendermint_tpu.utils import faults

            faults.tear_wal_tail(os.path.join(fn.home, "cs.wal"),
                                 mode=tear, seed=seed)
        self._crashed[idx] = (fn.priv, old_links)

    def reboot(self, idx: int, links: int = 3) -> int:
        """Boot a new incarnation of a hard-killed node from its abandoned
        durable home: handshake replay + WAL repair/replay recover the
        crash state, then consensus (or the stall watchdog's fast-sync
        hand-off) catches the node up. The new FabricNode generation makes
        the soak auditor re-verify the full prefix and exactly-once tx
        application. Returns the node's (unchanged) index."""
        crashed = self._crashed.pop(idx, None)
        if crashed is None:
            raise KeyError(f"node {idx} was not hard-killed")
        priv, old_links = crashed
        nfn = self._mk_node(idx, priv, fast_sync=False, joined_via="reboot")
        with self._lock:
            self.nodes[idx] = nfn
        nfn.node.start()
        for j in (old_links or sorted(self.nodes)[:links]):
            if j != idx and j in self.nodes:
                self.link(idx, j)
        return idx

    def set_skew(self, idx: int, skew_s: float) -> None:
        """Skew one node's clock (docs/NEMESIS.md ``skew`` action): every
        wall-clock read its consensus and evidence planes make shifts by
        ``skew_s`` seconds; 0 restores host time."""
        self.nodes[idx].node.clock.set_skew(skew_s)

    def block_time(self, i: int, h: int):
        """Header time of node ``i``'s block at height ``h`` (None when
        missing/quarantined) — the BFT-time monotonicity audit's read."""
        from tendermint_tpu.store.envelope import CorruptedStoreError

        try:
            meta = self.nodes[i].node.block_store.load_block_meta(h)
        except CorruptedStoreError:
            return None
        return None if meta is None else meta.header.time

    def promote(self, idx: int, power: int, via: int | None = None) -> bytes:
        """Change a validator's voting power through the ABCI path: submit
        the kvstore ``val:`` tx to a live node's mempool; EndBlock's
        validator_updates flow through state/execution.py and take effect
        two heights after the tx commits. Returns the tx bytes."""
        from tendermint_tpu.abci.kvstore import KVStoreApplication

        pub = self.nodes[idx].priv.pub_key()
        tx = KVStoreApplication.make_val_tx(pub.bytes(), power)
        self.submit_tx(tx, via)
        return tx

    def validator_power(self, idx: int, at: int | None = None) -> int:
        """Voting power of node ``idx``'s key in the current validator set
        of node ``at`` (default: the lowest live index)."""
        return self.validator_powers(at).get(idx, 0)

    def validator_powers(self, at: int | None = None) -> dict[int, int]:
        """index -> current voting power for every live node, from ONE
        state load (the soak driver's quorum arithmetic runs this per
        perturbation; per-node loads would be O(n) redundant I/O)."""
        at = min(self.nodes) if at is None else at
        st = self.nodes[at].node.state_store.load()
        by_pub = {v.pub_key.bytes(): v.voting_power
                  for v in st.validators.validators}
        return {i: by_pub.get(fn.priv.pub_key().bytes(), 0)
                for i, fn in self.nodes.items()}

    def byzantine_power_fraction(self, extra: set[int] | None = None) -> tuple[int, int]:
        """(byzantine power, total power) of the CURRENT validator set,
        counting ``extra`` indices as if already byzantine — the < 1/3
        guard every byzantine install runs behind."""
        powers = self.validator_powers()
        byz = self.byzantine | (extra or set())
        total = sum(max(p, 0) for p in powers.values())
        byz_power = sum(max(powers.get(i, 0), 0) for i in byz)
        return byz_power, total

    def install_byzantine(self, idx: int, spec: str = "double_prevote",
                          enforce_power: bool = True) -> None:
        """Make a live node byzantine per a consensus/misbehavior.py spec
        (``"equivocate~3-5+lunatic~7-"``, docs/BYZANTINE.md). The default
        guard refuses an install that would push aggregate byzantine power
        to >= 1/3 of the current set — the fabric's byzantine role exists
        to prove safety BELOW the BFT bound, not to fork the cluster;
        attack cookbooks that stage historic >= 1/3 coalitions do it
        through power churn, not by disabling the guard."""
        from tendermint_tpu.consensus import misbehavior as mb

        if enforce_power:
            byz_power, total = self.byzantine_power_fraction({idx})
            if total > 0 and 3 * byz_power >= total:
                raise ValueError(
                    f"byzantine install on node {idx} would put "
                    f"{byz_power}/{total} voting power under adversary "
                    f"control (>= 1/3); refuse (docs/BYZANTINE.md)")
        mb.install(self.nodes[idx].node, spec)
        self.byzantine.add(idx)

    def install_misbehavior(self, idx: int, name: str = "double_prevote") -> None:
        """Back-compat shim for the soak ``evidence`` action."""
        self.install_byzantine(idx, name)

    # --- light-client serving -----------------------------------------------

    def light_provider(self, idx: int, name: str | None = None):
        """A light-block Provider view of one fabric node, byzantine-aware:
        it mirrors the RPC ``light_block`` route's seam exactly — a node
        carrying ``byzantine_light_blocks`` (the lunatic_proposer staging
        map, docs/BYZANTINE.md) serves its FAKES first, else it reads the
        honest stores through NodeProvider (so corrupted rows surface as
        clean not-found, never rotten bytes). The provider resolves the
        node at call time, so restarts/reboots swap the backing object
        transparently and a hard-killed index answers ErrNoResponse."""
        return _FabricLightProvider(self, idx, name or f"node{idx}")

    # --- load ---------------------------------------------------------------

    def submit_tx(self, tx: bytes, via: int | None = None) -> bool:
        """CheckTx a transaction into one live node's mempool (gossip and
        the proposer path take it from there). Returns acceptance."""
        candidates = ([via] if via is not None else sorted(self.nodes))
        for i in candidates:
            fn = self.nodes.get(i)
            if fn is None:
                continue
            try:
                # the batched client path (docs/INGEST.md): every seeded
                # scenario's tx load exercises the coalesced front door
                res = fn.node.mempool.ingest_tx(tx)
                return bool(res is None or res.is_ok())
            except Exception:  # noqa: BLE001 - full/duplicate: try the next
                continue
        return False

    # --- resource budget ----------------------------------------------------

    def expected_thread_budget(self) -> int:
        from tendermint_tpu.mempool import ingest as _ingest

        per_peer = PER_PEER_THREADS + (
            PER_PEER_THREADS_MEMPOOL if self.mempool_broadcast else 0)
        peer_sides = sum(len(fn.links) for fn in self.nodes.values())
        per_node = NODE_BASE_THREADS + (1 if self.mempool_broadcast else 0) + (
            NODE_THREADS_INGEST if _ingest.enabled() else 0)
        rpc_listeners = (1 if self.rpc_node >= 0 else 0) + len(
            [i for i in self.rpc_nodes if i != self.rpc_node])
        extra = (1 if self.metrics_node >= 0 else 0) + 2 * rpc_listeners
        return len(self.nodes) * per_node + peer_sides * per_peer + extra

    def expected_fd_budget(self) -> int:
        links = sum(len(fn.links) for fn in self.nodes.values()) // 2
        return links * FDS_PER_LINK + len(self.nodes) * FDS_PER_NODE + 16

    def resource_report(self) -> dict:
        return {
            "nodes": len(self.nodes),
            "links": sum(len(fn.links) for fn in self.nodes.values()) // 2,
            "threads": threading.active_count() - self._baseline_threads,
            "thread_budget": self.expected_thread_budget(),
            "fds": max(0, _open_fds() - self._baseline_fds),
            "fd_budget": self.expected_fd_budget(),
        }

    def assert_resource_budget(self) -> dict:
        """The fabric-level budget assertion: the live process must not
        exceed what the topology predicts. A regression in per-node
        thread/fd footprint (say, a reactor growing a per-peer thread)
        fails HERE, at 4 nodes in the quick tier, instead of melting the
        100-node soak."""
        r = self.resource_report()
        assert r["threads"] <= r["thread_budget"], (
            f"thread budget exceeded: {r['threads']} live threads over a "
            f"budget of {r['thread_budget']} for {r['nodes']} nodes / "
            f"{r['links']} links — a per-peer or per-node thread regression "
            f"(see e2e/fabric.py budget constants)")
        assert r["fds"] <= r["fd_budget"], (
            f"fd budget exceeded: {r['fds']} fds over {r['fd_budget']} "
            f"for {r['nodes']} nodes / {r['links']} links")
        return r


class _FabricLightProvider:
    """Cluster.light_provider's duck-typed Provider (light/provider.py
    contract): call-time node resolution + the byzantine-fakes-first seam
    shared with the rpc/core.py ``light_block`` route."""

    def __init__(self, cluster: "Cluster", idx: int, name: str):
        self.cluster = cluster
        self.idx = idx
        self.name = name
        self.evidences: list = []

    def chain_id(self) -> str:
        return self.cluster.chain_id

    def _node(self):
        fn = self.cluster.nodes.get(self.idx)
        if fn is None:
            from tendermint_tpu.light.provider import ErrNoResponse

            raise ErrNoResponse(f"fabric node {self.idx} is down")
        return fn.node

    def light_block(self, height: int):
        from tendermint_tpu.light.provider import NodeProvider

        node = self._node()
        fakes = getattr(node, "byzantine_light_blocks", None)
        if fakes:
            lb = fakes.get(height or node.block_store.height)
            if lb is not None:
                return lb
        return NodeProvider(self.cluster.chain_id, node.block_store,
                            node.state_store).light_block(height)

    def report_evidence(self, ev) -> None:
        self.evidences.append(ev)
        # land it in the live pool too: the gateway's detector reports
        # flow into consensus exactly like an RPC broadcast_evidence
        try:
            self._node().evidence_pool.add_evidence(ev)
        except Exception:  # noqa: BLE001 - a down/byzantine sink is fine;
            # the detector already reported to the other side
            pass


def _open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0
