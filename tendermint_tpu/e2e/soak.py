"""Seeded long-soak harness over the scenario fabric (ROADMAP item 5;
reference: the nightly e2e matrix of test/e2e/ — but one long RUN composing
perturbations against sustained load, with the safety/liveness audit running
CONTINUOUSLY instead of only at scenario end).

A soak is: a :class:`~tendermint_tpu.e2e.fabric.Cluster`, a seeded
:class:`SoakSchedule` of composed perturbations (partition/heal, link
faults, flood, validator churn, power changes, restarts, equivocation),
sustained tx load, and a background :class:`ContinuousAuditor` thread that
re-checks full-prefix block-hash agreement and a liveness bound every few
hundred milliseconds — a fork that heals before scenario end is still a
safety violation, and only a continuous audit can see it.

**Determinism contract.** A schedule is a pure function of
``TMTPU_SOAK_SEED`` (plus node count and duration); every entry is
expressible in the schedule grammar below, and any violation prints ONE
repro line carrying the full knob set::

    TMTPU_SOAK_REPRO: TMTPU_FAULT_SEED=2026 TMTPU_SOAK_SEED=7 \
        TMTPU_SOAK_NODES=50 TMTPU_SOAK_TOPOLOGY=k-regular:6 \
        TMTPU_SOAK_DURATION_S=30 TMTPU_SOAK_SCHEDULE='@3:partition~2:4|rest;@9:join'

Re-running with those env vars replays the exact perturbation schedule
(thread interleavings still vary — same contract as the nemesis layer's
seeded link decisions).

**Schedule grammar** (``TMTPU_SOAK_SCHEDULE``; ``;``-separated entries)::

    @<t>:<kind>[~<dur>][:<arg>]

    @3:partition~2:4|rest        cut {4} from everyone, heal after 2 s
    @5:partition~1.5:0/1|2/3     explicit groups of node indices
    @8:linkfault~2:*>3:drop%0.5  seeded flaky link for 2 s
    @9:linkfault~2:*>3:delay~0.05  50 ms delay link (arg may contain ~)
    @10:flood~1.5:1>0            nemesis flood action on a link
    @12:join                     fast-sync joiner
    @12:join_statesync           statesync joiner (needs rpc_node+snapshots)
    @15:power:5:30               val-tx voting-power change via ABCI
    @18:restart:2                stop + re-boot a node (fast-sync recovery)
    @21:leave:6                  remove a node mid-height
    @24:evidence:3               make node 3 equivocate (double_prevote)
    @27:bitrot:2:block           flip a seeded bit in node 2's block store
    @28:bitrot:2:state:truncate  truncate a state-history row at rest
    @30:byz:5:double_precommit   full byzantine role: behavior spec on a node
    @33:byz:5:equivocate~8-12    height-windowed behavior map (misbehavior.py
                                 grammar; '+'-joined segments map behaviors)
    @36:crash~3:2                power-loss hard-kill node 2, reboot after 3 s
    @37:crash~3:4:torn           same, with a torn WAL tail on the dead home
    @39:crash~-1:5               machine LOST: hard-killed, never rebooted
    @42:crashstorm~3:2           hard-kill 2 seeded nodes at once, reboot all
    @45:skew~5:3:120             skew node 3's clock +120 s for 5 s
    @48:skew:3:-45               skew node 3 by -45 s for the rest of the run
    @50:lightcrowd~8:16          16 gateway light clients for 8 s (no dur:
                                 the crowd rides to the end of the soak)

The ``crash``/``crashstorm`` actions need a DURABLE cluster
(``Cluster(durable=True)``; ``run_soak(durable=True)`` /
``TMTPU_SOAK_DURABLE=1``): a hard kill abandons the node object with no
flush of any kind and a later reboot boots a NEW incarnation from the
on-disk home exactly as the crash left it, so the home must outlive the
process object (docs/SOAK.md crash cookbook). The downtime rides the
duration slot; a NEGATIVE duration means the machine is never rebooted —
the intentionally-unhealed form. Cutting quorum that way is a liveness
violation BY DESIGN (the minimizer's forced-failure fixture); a crash
whose survivors keep quorum, or one with a reboot pending, audits clean.
``skew`` drives one node's patchable time source (utils/clock.py): the
auditor then proves BFT time stays strictly monotone along the agreed
prefix (header time is the weighted median of commit vote times, so a
sub-1/3 skewed minority cannot bend it) and that no evidence is ever
expired by wall-clock age alone (``false-expiry``: the pool requires
BOTH the height bound and the duration bound to pass — block counts
cannot be skewed).

The ``byz`` action (and the legacy ``evidence`` shorthand) installs a
consensus/misbehavior.py behavior spec on a node (docs/BYZANTINE.md) and
marks it byzantine for the auditors. Both are guarded: an install that
would push aggregate byzantine power to >= 1/3 of the current set is
SKIPPED (the soak proves safety below the BFT bound, it does not fork
itself), and byzantine nodes count as non-voting in the driver's quorum
arithmetic (their participation is adversary-controlled, so a partition
that leaves the honest side short of 2/3 is an expected stall).

The :class:`ContinuousAuditor` additionally audits the EVIDENCE LIFECYCLE
under byzantium: every piece of evidence committed by any honest node must
be committed by EVERY honest node exactly once within
``TMTPU_BYZ_EVIDENCE_BOUND`` heights of its first commit (a provoked
misbehavior that converges on some nodes but not others, or lands twice,
is a violation — flight-recorder-annotated like a liveness stall), and the
block-hash agreement audit covers the HONEST prefix only.

The ``lightcrowd`` action attaches a crowd of concurrent light clients to
a :class:`~tendermint_tpu.light.gateway.LightGateway` built over the live
fabric and rides it through whatever else the schedule composes. Its audit
face is the WRONG-ANSWER invariant: every verified answer any client
receives must match the block hash the honest cluster agreed at that
height, and every client must receive the same answer — a gateway may
refuse (typed degradation) but must never lie (docs/LIGHT.md).

The driver tracks quorum arithmetic: while an installed partition leaves no
side with >2/3 of the voting power, the auditor is told a stall is EXPECTED
(that freeze is the safety property, not a liveness bug); heal restores the
liveness clock after a grace window. See docs/SOAK.md for the cookbook.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field

from tendermint_tpu.consensus import misbehavior
from tendermint_tpu.e2e.fabric import Cluster
from tendermint_tpu.utils import faults, nemesis

DEFAULT_NODES = 8
DEFAULT_DURATION_S = 20.0
DEFAULT_TOPOLOGY = "k-regular:4"

_KINDS = ("partition", "linkfault", "flood", "join", "join_statesync",
          "power", "restart", "leave", "evidence", "bitrot", "byz",
          "crash", "crashstorm", "skew", "lightcrowd")

# actions that only make sense against a durable cluster: a hard kill
# abandons the live object and reboots from the on-disk home
_DURABLE_KINDS = ("crash", "crashstorm")

# the behaviors a seeded schedule cycles byzantine nodes through: derived
# from the authoritative catalog (a behavior added there is exercised by
# generated soaks automatically), minus the absent_prevote alias
_BYZ_BEHAVIORS = tuple(b for b in misbehavior.BEHAVIORS
                       if b != "absent_prevote")


@dataclass
class SoakAction:
    """One schedule entry: ``@<t>:<kind>[~<dur>][:<arg>]``. The duration
    rides on the KIND segment (never the arg): link-fault args legally
    contain ``~`` themselves (``delay~0.05`` is nemesis grammar), so a
    trailing-``~`` duration would be ambiguous."""

    at_s: float
    kind: str
    arg: str = ""
    dur_s: float = 0.0

    def describe(self) -> str:
        out = f"@{self.at_s:g}:{self.kind}"
        if self.dur_s:
            out += f"~{self.dur_s:g}"
        if self.arg:
            out += f":{self.arg}"
        return out

    @staticmethod
    def parse(entry: str) -> "SoakAction":
        entry = entry.strip()
        if not entry.startswith("@"):
            raise ValueError(f"bad soak entry {entry!r} (want @t:kind[~dur][:arg])")
        head, _, rest = entry[1:].partition(":")
        kind_part, _, arg = rest.partition(":")
        kind, _, d = kind_part.partition("~")
        dur = float(d) if d else 0.0
        if kind not in _KINDS:
            raise ValueError(f"unknown soak action {kind!r} in {entry!r}")
        return SoakAction(at_s=float(head), kind=kind, arg=arg, dur_s=dur)


class SoakSchedule:
    """An ordered list of :class:`SoakAction`; seeded generation and a
    parse/describe round trip so a printed repro line IS the schedule."""

    def __init__(self, actions: list[SoakAction]):
        self.actions = sorted(actions, key=lambda a: a.at_s)

    def describe(self) -> str:
        return ";".join(a.describe() for a in self.actions)

    @staticmethod
    def parse(spec: str) -> "SoakSchedule":
        return SoakSchedule([SoakAction.parse(e)
                             for e in spec.split(";") if e.strip()])

    @staticmethod
    def generate(seed: int, duration_s: float, nodes: int,
                 statesync_ok: bool = False,
                 durable: bool = False) -> "SoakSchedule":
        """A deterministic composed-perturbation schedule. Partitions only
        ever cut a sub-1/3 minority (the majority keeps committing, so the
        liveness bound stays armed through them); churn actions target
        joiners and high indices so genesis quorum is never destroyed.
        ``durable`` adds the power-loss vocabulary (crash/crashstorm):
        generated crashes always reboot and never tear down more than a
        sub-1/3 minority at once, so the audit stays armed through them."""
        rng = random.Random(f"soak:{seed}:{nodes}:{duration_s:g}")
        actions: list[SoakAction] = []
        joined = 0
        # byzantine bookkeeping: the generator never schedules an
        # aggregate adversary of >= 1/3 (equal genesis powers, so the
        # bound is a node count: 3 * (byz + 1) < nodes); behaviors cycle
        # deterministically so a long soak walks the whole catalog
        byz_nodes: list[int] = []
        byz_cycle = 0
        # one perturbation every ~duration/7, starting after a warm-up
        slots = max(3, int(duration_s / max(duration_s / 7.0, 2.0)))
        step = duration_s * 0.7 / slots
        t = duration_s * 0.15
        kinds = ["partition", "linkfault", "join", "power", "flood",
                 "restart", "evidence", "bitrot", "byz", "skew",
                 "lightcrowd"]
        if statesync_ok:
            kinds.append("join_statesync")
        if durable:
            # weight the crash plane like any other kind; storms stay rare
            kinds += ["crash", "crash", "crashstorm"]
        for _ in range(slots):
            t += step * (0.6 + 0.8 * rng.random())
            if t >= duration_s * 0.9:
                break
            kind = rng.choice(kinds)
            dur = round(min(step, 1.0 + 2.0 * rng.random()), 1)
            if kind == "partition":
                cut = rng.sample(range(nodes), max(1, (nodes - 1) // 4))
                arg = "/".join(str(i) for i in sorted(cut)) + "|rest"
                actions.append(SoakAction(round(t, 1), kind, arg, dur))
            elif kind == "linkfault":
                dst = rng.randrange(nodes)
                act = rng.choice(("drop%0.5", "delay~0.05", "dup"))
                actions.append(SoakAction(round(t, 1), kind,
                                          f"*>{dst}:{act}", dur))
            elif kind == "flood":
                a, b = rng.sample(range(nodes), 2)
                actions.append(SoakAction(round(t, 1), kind, f"{a}>{b}", dur))
            elif kind in ("join", "join_statesync"):
                joined += 1
                actions.append(SoakAction(round(t, 1), kind))
            elif kind == "power":
                # grow a node's power mildly, or promote a joiner
                idx = (nodes + joined - 1) if joined else rng.randrange(nodes)
                actions.append(SoakAction(round(t, 1), kind,
                                          f"{idx}:{rng.choice((5, 15, 20))}"))
            elif kind == "restart":
                actions.append(SoakAction(round(t, 1), kind,
                                          str(rng.randrange(nodes))))
            elif kind in ("evidence", "byz"):
                # both kinds grow the adversary: share the < 1/3 ledger.
                # Re-target an existing byzantine node (cycling its
                # behavior) when growing the coalition would cross 1/3.
                if byz_nodes and (rng.random() < 0.5
                                  or 3 * (len(byz_nodes) + 1) >= nodes):
                    target = rng.choice(byz_nodes)
                elif 3 * (len(byz_nodes) + 1) < nodes:
                    target = rng.choice([i for i in range(nodes)
                                         if i not in byz_nodes])
                    byz_nodes.append(target)
                else:
                    continue  # a 3-node net has no room for an adversary
                if kind == "evidence":
                    actions.append(SoakAction(round(t, 1), kind, str(target)))
                else:
                    behavior = _BYZ_BEHAVIORS[byz_cycle % len(_BYZ_BEHAVIORS)]
                    byz_cycle += 1
                    actions.append(SoakAction(round(t, 1), kind,
                                              f"{target}:{behavior}"))
            elif kind == "crash":
                # generated crashes ALWAYS reboot (positive downtime) and
                # only one machine dies per action: the fault-free majority
                # keeps committing, so the liveness audit stays armed
                target = rng.randrange(nodes)
                tear = rng.choice(("", "", ":torn", ":partial"))
                actions.append(SoakAction(round(t, 1), kind,
                                          f"{target}{tear}",
                                          round(1.0 + 2.0 * rng.random(), 1)))
            elif kind == "crashstorm":
                # storm size capped at a sub-1/3 minority so the survivors
                # keep quorum even while every victim is down at once
                k = max(1, min((nodes - 1) // 3, 1 + rng.randrange(2)))
                actions.append(SoakAction(round(t, 1), kind, str(k),
                                          round(1.0 + 2.0 * rng.random(), 1)))
            elif kind == "skew":
                target = rng.randrange(nodes)
                secs = rng.choice((-90, -30, 45, 120, 600))
                actions.append(SoakAction(round(t, 1), kind,
                                          f"{target}:{secs}", dur))
            elif kind == "lightcrowd":
                # a crowd of gateway light clients riding whatever else
                # the schedule composes: every verified answer is checked
                # against the agreed honest prefix (docs/LIGHT.md)
                actions.append(SoakAction(round(t, 1), kind,
                                          str(rng.choice((4, 8, 16))),
                                          round(dur + 2.0, 1)))
            elif kind == "bitrot":
                # at-rest corruption of one node's storage plane: the
                # scrubber must detect it and the repairer heal it with
                # ZERO auditor violations (docs/DURABILITY.md)
                target = rng.randrange(nodes)
                store = rng.choice(("block", "block", "state"))
                mode = rng.choice(("bitrot", "truncate"))
                actions.append(SoakAction(round(t, 1), kind,
                                          f"{target}:{store}:{mode}"))
        return SoakSchedule(actions)


# --- continuous auditing -----------------------------------------------------


@dataclass
class Violation:
    kind: str      # "fork" | "liveness" | "audit" | "evidence"
                   # | "bft-time" | "false-expiry" | "wrong-answer"
    detail: str
    at_s: float = 0.0

    def __str__(self) -> str:
        return f"[{self.kind} @{self.at_s:.1f}s] {self.detail}"


DEFAULT_EVIDENCE_BOUND = 8  # heights; TMTPU_BYZ_EVIDENCE_BOUND overrides


class ContinuousAuditor:
    """Background safety/liveness auditor over a live cluster.

    Safety: incremental full-prefix agreement — the first node to commit
    height h pins the cluster-wide hash for h; every other node's commit of
    h is checked against it (including heights committed DURING partitions,
    which an end-of-scenario audit of a healed cluster would also catch,
    but hours later). Restarted node objects re-verify their whole prefix.

    Liveness: the max committed height must advance within
    ``liveness_budget_s`` whenever the driver hasn't declared a stall
    expected (a quorum-cutting partition window + heal grace).

    Evidence lifecycle (docs/BYZANTINE.md): every piece of evidence any
    honest node commits must be committed by EVERY honest node exactly
    once within ``evidence_bound`` heights of its first commit. Exactly-
    once catches a pool that re-admits committed evidence; the convergence
    bound catches evidence that verified on some honest nodes but not
    others (a determinism bug in verification — the one detection
    machinery divergence a fork audit can't see). Both safety sweeps skip
    byzantine nodes: the promises are about the honest prefix.

    Clock-skew invariants (the ``skew`` action's audit face): BFT time must
    stay STRICTLY monotone along the agreed prefix — header time is the
    weighted median of the commit's vote timestamps and validation pins it
    above ``last_block_time``, so a sub-1/3 skewed minority must not be
    able to bend it (kind ``bft-time``) — and no evidence pool may expire
    evidence on wall-clock age alone: every entry in a pool's
    ``expired_log`` must show the HEIGHT bound exceeded too, because block
    counts cannot be skewed (kind ``false-expiry``).
    """

    def __init__(self, cluster: Cluster, liveness_budget_s: float = 30.0,
                 poll_s: float = 0.3, evidence_bound: int | None = None,
                 logger=None):
        self.cluster = cluster
        self.liveness_budget_s = liveness_budget_s
        self.poll_s = poll_s
        self.logger = logger
        self.violations: list[Violation] = []
        self.heights_audited = 0
        self.evidence_audited = 0   # distinct committed evidence tracked
        self.evidence_bound = (evidence_bound if evidence_bound is not None
                               else int(os.environ.get(
                                   "TMTPU_BYZ_EVIDENCE_BOUND",
                                   DEFAULT_EVIDENCE_BOUND)))
        self._agreed: dict[int, bytes] = {}
        self._checked: dict[int, tuple[int, int]] = {}  # idx -> (node id(), h)
        # BFT-time monotonicity books: height -> header time (Time) read
        # once when the height is first agreed; flag set = reported once
        self._agreed_t: dict[int, object] = {}
        self._time_flagged: set[int] = set()
        # false-expiry books: idx -> (gen key, # expired_log entries seen)
        self._exp_scanned: dict[int, tuple] = {}
        # evidence lifecycle books: hash -> {idx: [commit heights]},
        # hash -> first commit height, plus flags so each anomaly reports
        # exactly once per (evidence, node)
        self._ev_seen: dict[bytes, dict[int, list[int]]] = {}
        self._ev_first: dict[bytes, int] = {}
        self._ev_scanned: dict[int, tuple] = {}  # idx -> (gen key, height)
        self._ev_flagged: set = set()            # (hash, idx) pairs reported
        self._ev_converged: set = set()
        # wrong-answer books (the lightcrowd invariant): height -> the
        # first verified answer any gateway client got (hash, who); a
        # height reports at most once
        self._light_answers: dict[int, tuple[bytes, str]] = {}
        self._light_flagged: set[int] = set()
        self._light_mtx = threading.Lock()
        self.light_answers_audited = 0
        self._t0 = 0.0
        self._last_advance = 0.0
        self._best = 0
        self._stall_ok_until = 0.0
        self._stall_ok = False
        self._stalled_reported = False
        self._running = False
        self._thread: threading.Thread | None = None

    # the driver flips this around quorum-cutting perturbation windows
    def expect_stall(self, on: bool, grace_s: float = 10.0) -> None:
        self._stall_ok = on
        if not on:
            self._stall_ok_until = time.monotonic() + grace_s
            self._last_advance = time.monotonic()

    def start(self) -> None:
        self._t0 = self._last_advance = time.monotonic()
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name="soak-auditor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while self._running:
            try:
                self.sweep()
            except Exception as e:  # noqa: BLE001 - the auditor must outlive
                # any mid-churn race (a node torn down between snapshot and
                # read); a sweep error is just retried next tick
                if self.logger:
                    self.logger.error("auditor sweep failed", err=e)
            time.sleep(self.poll_s)

    def _record(self, kind: str, detail: str) -> None:
        self.violations.append(
            Violation(kind, detail, at_s=time.monotonic() - self._t0))

    def _lag_annotation(self) -> str:
        """Per-lagging-node last-completed phase from each node's flight
        recorder (utils/trace.py): a liveness violation that says WHICH
        phase of WHICH height every straggler last finished is a repro
        line an engineer can act on. Nodes without an enabled tracer
        annotate as `last_phase=?` (the harness builds clusters with
        trace=True; a stub or opted-out node degrades gracefully)."""
        parts = []
        try:
            for idx, fn in sorted(self.cluster.nodes.items()):
                if fn.height >= self._best:
                    continue
                tracer = getattr(fn.node, "tracer", None)
                lp = (tracer.last_phase()
                      if tracer is not None and getattr(tracer, "enabled",
                                                        False) else None)
                if lp is None:
                    parts.append(f"node {idx}@h{fn.height} last_phase=?")
                else:
                    at = (f"(h{lp['height']})"
                          if lp.get("height") is not None else "")
                    parts.append(
                        f"node {idx}@h{fn.height} last_phase={lp['name']}"
                        f"{at} {lp['age_s']:.1f}s ago")
        except Exception:  # noqa: BLE001 - annotation must never mask the
            # violation it decorates (mid-churn teardown races)
            pass
        return "; ".join(parts)

    # --- the wrong-answer invariant (lightcrowd action, docs/LIGHT.md) ------

    def note_light_answer(self, height: int, block_hash: bytes,
                          who: str) -> None:
        """Called by gateway light clients for every VERIFIED answer they
        receive. Two invariants: (a) all clients get the SAME verified
        answer per height (checked immediately — the first answer pins
        it), and (b) that answer matches the hash the honest cluster
        agreed at that height (checked against ``_agreed`` as heights get
        pinned, in :meth:`_sweep_light_answers`). A violation here means
        a gateway handed out a header that passed light-client
        verification but diverges from the honest chain — the exact
        failure the witness/detector plane exists to make impossible."""
        with self._light_mtx:
            prev = self._light_answers.get(height)
            if prev is None:
                self._light_answers[height] = (block_hash, who)
                self.light_answers_audited += 1
                return
            if prev[0] == block_hash or height in self._light_flagged:
                return
            self._light_flagged.add(height)
        self._record("wrong-answer",
                     f"two verified answers at height {height}: "
                     f"{prev[0].hex()[:16]} ({prev[1]}) vs "
                     f"{block_hash.hex()[:16]} ({who})")

    def _sweep_light_answers(self) -> None:
        with self._light_mtx:
            pending = [(h, bh, who)
                       for h, (bh, who) in self._light_answers.items()
                       if h not in self._light_flagged]
        for h, bh, who in pending:
            agreed = self._agreed.get(h)
            if agreed is None or agreed == bh:
                continue
            with self._light_mtx:
                if h in self._light_flagged:
                    continue
                self._light_flagged.add(h)
            lag = self._lag_annotation()
            self._record("wrong-answer",
                         f"gateway served {bh.hex()[:16]} at height {h} "
                         f"({who}) but the honest cluster agreed "
                         f"{agreed.hex()[:16]}"
                         + (f" [lagging: {lag}]" if lag else ""))

    def sweep(self) -> None:
        """One audit pass (public so tests and the final drain call it
        synchronously)."""
        byz = getattr(self.cluster, "byzantine", set())
        nodes = sorted(self.cluster.nodes.items())
        best = self._best
        for idx, fn in nodes:
            if idx in byz:
                # safety-under-byzantium is a promise about the HONEST
                # prefix; a byzantine node's store stays off the agreement
                # ledger (its tip still feeds the liveness clock below)
                best = max(best, fn.height)
                continue
            # FabricNode carries a process-monotonic generation; id() alone
            # can be REUSED by the allocator after the old Node is
            # collected, which would silently skip a restarted node's
            # full-prefix re-verification
            key = (getattr(fn, "generation", None), id(fn.node))
            prev_key, prev_h = self._checked.get(idx, (key, 0))
            start_h = prev_h + 1 if prev_key == key else 1  # restart: re-verify
            # a pruned store (statesync joiner) legitimately has nothing
            # below its base — starting there keeps the stop-on-missing
            # rule below from retrying unpersisted-looking heights forever
            store = getattr(fn.node, "block_store", None)
            start_h = max(start_h, getattr(store, "base", 1) or 1)
            tip = fn.height
            checked_to = start_h - 1
            for h in range(start_h, tip + 1):
                bh = self.cluster.block_hash(idx, h)
                if bh is None:
                    # store height is bumped before the meta persists:
                    # stop HERE and re-read this height next sweep —
                    # skipping past it would leave the node's commit of h
                    # permanently unaudited (a fork there could then leave
                    # with the node before the final audit sees it)
                    break
                checked_to = h
                agreed = self._agreed.get(h)
                if agreed is None:
                    self._agreed[h] = bh
                    self.heights_audited += 1
                    self._check_bft_time(idx, h)
                elif bh != agreed:
                    self._record("fork",
                                 f"height {h}: node {idx} committed "
                                 f"{bh.hex()[:16]}, cluster agreed "
                                 f"{agreed.hex()[:16]}")
            self._checked[idx] = (key, checked_to)
            best = max(best, tip)
        self._sweep_evidence(byz)
        self._sweep_expiry(byz)
        self._sweep_light_answers()
        now = time.monotonic()
        if best > self._best:
            self._best = best
            self._last_advance = now
            self._stalled_reported = False
        elif (not self._stall_ok and now > self._stall_ok_until
              and now - self._last_advance > self.liveness_budget_s
              and not self._stalled_reported):
            self._stalled_reported = True  # once per stall episode
            lag = self._lag_annotation()
            self._record("liveness",
                         f"no commit cluster-wide for "
                         f"{now - self._last_advance:.1f}s "
                         f"(budget {self.liveness_budget_s:.0f}s) at "
                         f"height {self._best}"
                         + (f" [lagging: {lag}]" if lag else ""))

    # --- clock-skew invariants (docs/SOAK.md skew cookbook) -----------------

    def _check_bft_time(self, idx: int, h: int) -> None:
        """Strict BFT-time monotonicity along the agreed prefix, read once
        per height as it is first pinned (prefix agreement makes every
        node's copy of h the SAME block, so one read suffices). Checked in
        both directions because a statesync joiner can pin a high height
        before any full node pins the one below it."""
        read = getattr(self.cluster, "block_time", None)
        if read is None:
            return  # stub cluster (unit tests): no header times to audit
        t = read(idx, h)
        if t is None:
            return  # meta not persisted yet / quarantined: next sweep
        self._agreed_t[h] = t
        for a, b in ((h - 1, h), (h, h + 1)):
            ta, tb = self._agreed_t.get(a), self._agreed_t.get(b)
            if (ta is not None and tb is not None and not tb > ta
                    and b not in self._time_flagged):
                self._time_flagged.add(b)
                self._record(
                    "bft-time",
                    f"header time not strictly increasing: height {b} "
                    f"time {tb} <= height {a} time {ta} (a skewed "
                    f"proposer bent the weighted-median clock)")

    def _sweep_expiry(self, byz: set) -> None:
        """False-expiry audit: every entry a pool logs when it expires
        evidence must show the HEIGHT bound exceeded too, not just the
        wall-clock one — ages in blocks cannot be skewed, so a time-only
        expiry means a skewed clock (or a pool bug) silently dropped
        punishable evidence before its height window closed."""
        for idx, fn in sorted(self.cluster.nodes.items()):
            if idx in byz:
                continue
            pool = getattr(getattr(fn, "node", None), "evidence_pool", None)
            log = getattr(pool, "expired_log", None)
            if not log:
                continue
            key = (getattr(fn, "generation", None), id(fn.node))
            prev_key, seen = self._exp_scanned.get(idx, (key, 0))
            if prev_key != key:
                seen = 0  # new incarnation logs from scratch
            entries = list(log)
            for e in entries[min(seen, len(entries)):]:
                if e["age_blocks"] <= e["max_age_num_blocks"]:
                    self._record(
                        "false-expiry",
                        f"node {idx} expired evidence from height "
                        f"{e['height']} after only {e['age_blocks']} "
                        f"blocks (limit {e['max_age_num_blocks']}): "
                        f"expiry on wall-clock age alone")
            self._exp_scanned[idx] = (key, len(entries))

    # --- evidence-lifecycle convergence (docs/BYZANTINE.md) -----------------

    def _sweep_evidence(self, byz: set) -> None:
        """Incrementally scan each honest node's newly committed blocks for
        evidence, then check the exactly-once + bounded-convergence
        invariants. Incremental like the fork sweep: each (node, height)
        block is read once per node generation."""
        honest = {i: fn for i, fn in sorted(self.cluster.nodes.items())
                  if i not in byz}
        for idx, fn in honest.items():
            key = (getattr(fn, "generation", None), id(fn.node))
            prev_key, prev_h = self._ev_scanned.get(idx, (key, 0))
            start_h = prev_h + 1 if prev_key == key else 1
            store = getattr(fn.node, "block_store", None)
            start_h = max(start_h, getattr(store, "base", 1) or 1)
            scanned_to = start_h - 1
            for h in range(start_h, fn.height + 1):
                try:
                    block = store.load_block(h)
                except Exception:  # noqa: BLE001 - quarantined/rotten row:
                    block = None   # re-read next sweep like the fork audit
                if block is None:
                    break  # mid-persist tip: stop, retry next sweep
                scanned_to = h
                for ev in block.evidence:
                    evh = ev.hash()
                    rec = self._ev_seen.setdefault(evh, {})
                    if evh not in self._ev_first:
                        self._ev_first[evh] = h
                        self.evidence_audited += 1
                    heights = rec.setdefault(idx, [])
                    if h not in heights:
                        # dedup by height: a restarted node's full-prefix
                        # rescan re-reads the SAME carrying block — only a
                        # commit at a second height is a real re-admission
                        # (one block can't carry the same evidence twice;
                        # check_evidence dedups in-block)
                        heights.append(h)
                    if len(rec[idx]) > 1 and (evh, idx) not in self._ev_flagged:
                        self._ev_flagged.add((evh, idx))
                        self._record(
                            "evidence",
                            f"evidence {evh.hex()[:16]} committed TWICE on "
                            f"node {idx} (heights {rec[idx]}): the pool "
                            f"re-admitted committed evidence")
            self._ev_scanned[idx] = (key, scanned_to)
        # convergence: once any honest node's scanned prefix is `bound`
        # heights past an evidence's first commit, every honest node whose
        # prefix also covers that window must carry it
        for evh, first_h in self._ev_first.items():
            if evh in self._ev_converged:
                continue
            rec = self._ev_seen.get(evh, {})
            deadline = first_h + self.evidence_bound
            overdue = []
            missing = False
            for idx, fn in honest.items():
                if (getattr(fn.node.block_store, "base", 1) or 1) > first_h:
                    continue  # statesync joiner: its pruned prefix
                    # legitimately never contains the carrying block
                if idx in rec:
                    continue
                missing = True
                _, scanned_to = self._ev_scanned.get(idx, (None, 0))
                if scanned_to >= deadline:
                    overdue.append(idx)
            if not missing:
                self._ev_converged.add(evh)
                continue
            for idx in overdue:
                if (evh, idx) in self._ev_flagged:
                    continue
                self._ev_flagged.add((evh, idx))
                lag = self._lag_annotation()
                self._record(
                    "evidence",
                    f"evidence {evh.hex()[:16]} (first committed at height "
                    f"{first_h}) missing on node {idx} past the "
                    f"{self.evidence_bound}-height convergence bound"
                    + (f" [lagging: {lag}]" if lag else ""))


# --- the light-client crowd (lightcrowd action) ------------------------------


class LightCrowd:
    """A crowd of concurrent light clients riding one LightGateway built
    over the live fabric (docs/LIGHT.md): the serving-plane face of the
    soak. Each client thread hammers seeded height queries (plus the odd
    latest-head refresh) while the schedule composes partitions, churn,
    bitrot and byzantine behavior underneath; every VERIFIED answer is
    reported to the auditor's wrong-answer invariant. The provider pool
    deliberately includes byzantine nodes — they are the liars the
    gateway's scoreboard must demote and evict mid-crowd — but the
    PRIMARY and the trust anchor come from an honest node (a light client
    bootstraps from a trusted anchor by definition; docs/LIGHT.md)."""

    def __init__(self, cluster: Cluster, auditor: ContinuousAuditor,
                 n_clients: int = 8, seed: int = 0, logger=None):
        self.cluster = cluster
        self.auditor = auditor
        self.n_clients = n_clients
        self.seed = seed
        self.logger = logger
        self.gateway = None
        self.queries = 0
        self.served = 0
        self.refused = 0
        self.verdicts: dict[str, int] = {}
        self._mtx = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        from tendermint_tpu.light.client import TrustOptions
        from tendermint_tpu.light.gateway import LightGateway
        from tendermint_tpu.light.store import DBStore
        from tendermint_tpu.store.db import MemDB

        byz = getattr(self.cluster, "byzantine", set())
        honest = [i for i in sorted(self.cluster.nodes) if i not in byz]
        if not honest:
            raise RuntimeError("lightcrowd needs an honest node to anchor on")
        pool = honest[:1] + [i for i in sorted(self.cluster.nodes)
                             if i != honest[0]]
        providers = [self.cluster.light_provider(i) for i in pool[:6]]
        # bootstrap like a real light client: anchor on the EARLIEST
        # still-in-trust-period header and verify forward — the posture
        # that actually exercises skipping verification (and that a
        # posterior-corruption lunatic attacks); block 1 carries the
        # genesis timestamp, which may predate the trust period
        from tendermint_tpu.light.verifier import header_expired
        from tendermint_tpu.types.ttime import Time

        period_s = 168 * 3600
        anchor = providers[0].light_block(0)
        node0 = self.cluster.nodes[honest[0]].node
        base = max(node0.block_store.base, 1)
        now = Time.now()
        for h in range(base, min(anchor.height, base + 16)):
            lb = providers[0].light_block(h)
            if not header_expired(lb.signed_header, period_s, now):
                anchor = lb
                break
        opts = TrustOptions(period_s=period_s, height=anchor.height,
                            hash=anchor.hash())
        self.gateway = LightGateway(
            self.cluster.chain_id, opts, providers,
            DBStore(MemDB(), self.cluster.chain_id),
            provider_names=[p.name for p in providers],
            node=self.cluster.nodes[honest[0]].node,
            seed=self.seed, logger=self.logger)
        for c in range(self.n_clients):
            th = threading.Thread(target=self._client, args=(c,),
                                  name=f"lightcrowd-{c}", daemon=True)
            self._threads.append(th)
            th.start()

    def _client(self, c: int) -> None:
        rng = random.Random(f"lightcrowd:{self.seed}:{c}")
        while not self._stop.is_set():
            tip = max(self.cluster.max_height(), 1)
            height = 0 if rng.random() < 0.1 else rng.randint(1, tip)
            try:
                if height == 0:
                    lb, verdict = self.gateway.serve_latest()
                else:
                    lb, verdict = self.gateway.serve_light_block(height)
            except Exception:  # noqa: BLE001 - refuse-over-lie IS the
                # contract: degraded/typed errors are a served "no", only
                # a wrong VERIFIED answer is a violation
                with self._mtx:
                    self.queries += 1
                    self.refused += 1
            else:
                with self._mtx:
                    self.queries += 1
                    self.served += 1
                    self.verdicts[verdict] = self.verdicts.get(verdict, 0) + 1
                self.auditor.note_light_answer(
                    lb.height, lb.hash(), f"client {c} verdict={verdict}")
            self._stop.wait(0.02 + 0.05 * rng.random())

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=5.0)

    def stats(self) -> dict:
        with self._mtx:
            out = {"clients": self.n_clients, "queries": self.queries,
                   "served": self.served, "refused": self.refused,
                   "verdicts": dict(self.verdicts)}
        if self.gateway is not None:
            d = self.gateway.describe()
            out["gateway"] = {"counters": d["counters"],
                              "evicted": d["providers"]["evicted"],
                              "rebuilds": d["counters"]["rebuilds"]}
        return out


# --- the driver --------------------------------------------------------------


@dataclass
class SoakReport:
    seed: int
    nodes: int
    topology: str
    duration_s: float
    schedule: str
    heights: dict = field(default_factory=dict)
    heights_audited: int = 0
    evidence_audited: int = 0
    byzantine: list = field(default_factory=list)
    txs_submitted: int = 0
    actions_fired: int = 0
    violations: list = field(default_factory=list)
    light: dict = field(default_factory=dict)  # lightcrowd serving stats
    repro: str = ""

    @property
    def ok(self) -> bool:
        return not self.violations


def repro_line(seed: int, nodes: int, topology: str, duration_s: float,
               schedule: str, statesync: bool = False,
               durable: bool = False) -> str:
    """The single-line deterministic replay spec printed on any failure.
    Carries EVERY knob the run was built from — including the statesync
    flag, which implies the serving-node RPC + app-snapshot cluster
    config a join_statesync action needs on replay, and the durable flag
    the crash actions need (on-disk homes that outlive the node object)."""
    return (f"TMTPU_SOAK_REPRO: TMTPU_FAULT_SEED={faults.REGISTRY.seed} "
            f"TMTPU_SOAK_SEED={seed} TMTPU_SOAK_NODES={nodes} "
            f"TMTPU_SOAK_TOPOLOGY={topology} "
            f"TMTPU_SOAK_DURATION_S={duration_s:g} "
            + (f"TMTPU_SOAK_STATESYNC=1 " if statesync else "")
            + (f"TMTPU_SOAK_DURABLE=1 " if durable else "")
            + f"TMTPU_SOAK_SCHEDULE='{schedule}'")


class SoakDriver:
    """Runs a schedule against a live cluster under sustained tx load with
    the continuous auditor attached."""

    def __init__(self, cluster: Cluster, schedule: SoakSchedule, seed: int,
                 duration_s: float, tx_interval_s: float = 0.15,
                 liveness_budget_s: float = 30.0, logger=None):
        self.cluster = cluster
        self.schedule = schedule
        self.seed = seed
        self.duration_s = duration_s
        self.tx_interval_s = tx_interval_s
        self.logger = logger
        self.auditor = ContinuousAuditor(
            cluster, liveness_budget_s=liveness_budget_s, logger=logger)
        # (due time, what, payload): payload is the exact LinkRule list a
        # scheduled link fault installed, so its expiry removes only THOSE
        # rules — a global clear would wipe overlapping faults early, and
        # nemesis.heal() deliberately leaves link rules standing
        self._pending_heals: list[tuple[float, str, object]] = []
        self._crowds: list[LightCrowd] = []
        self.txs = 0
        self.fired = 0

    # --- quorum arithmetic: is a stall EXPECTED under this partition? -------

    def _quorum_cut(self, groups: list[list[int]]) -> bool:
        # byzantine nodes count as NON-voting: their participation is
        # adversary-controlled (absent, equivocating, ...), so any side
        # that needs byzantine votes to reach 2/3 must be treated as
        # quorum-less — a stall there is the safety property, not a bug
        byz = getattr(self.cluster, "byzantine", set())
        powers = {i: (0 if i in byz else max(p, 0))
                  for i, p in self.cluster.validator_powers().items()}
        total = sum(powers.values())
        if total <= 0:
            return False
        grouped = [sum(powers.get(i, 0) for i in g) for g in groups]
        return not any(3 * p > 2 * total for p in grouped)

    def _byz_install_ok(self, idx: int) -> bool:
        """The < 1/3 aggregate guard for scheduled byzantine installs: a
        seeded schedule must never fork the cluster it audits."""
        byz_power, total = self.cluster.byzantine_power_fraction({idx})
        if total > 0 and 3 * byz_power >= total:
            if self.logger:
                self.logger.info("soak: skipping byzantine install",
                                 node=idx, byz_power=byz_power, total=total)
            return False
        return True

    def _groups_from_arg(self, arg: str) -> list[list[int]]:
        """``4|rest`` or ``0/1|2/3`` -> index groups; ``rest`` expands to
        every live index not named elsewhere."""
        named: set[int] = set()
        raw = arg.split("|")
        out: list[list[int]] = []
        for g in raw:
            if g == "rest":
                out.append([])  # placeholder
                continue
            idxs = [int(p) for p in g.split("/") if p]
            named.update(idxs)
            out.append(idxs)
        rest = [i for i in sorted(self.cluster.nodes) if i not in named]
        return [g if g else rest for g in out]

    # --- actions ------------------------------------------------------------

    def _apply(self, a: SoakAction, now: float) -> None:
        self.fired += 1
        if self.logger:
            self.logger.info("soak action", action=a.describe())
        if a.kind == "partition":
            groups = self._groups_from_arg(a.arg)
            live = [[i for i in g if i in self.cluster.nodes] for g in groups]
            if self._quorum_cut(live):
                self.auditor.expect_stall(True)
            self.cluster.partition(live)
            self._pending_heals.append((now + (a.dur_s or 2.0), "heal", None))
        elif a.kind == "linkfault":
            src_dst, _, act = a.arg.partition(":")
            src, _, dst = src_dst.partition(">")
            src = src if src == "*" else int(src)
            dst = dst if dst == "*" else int(dst)
            # a named endpoint may be mid-crash (hard-killed, reboot
            # pending): a link fault against a dead machine is a no-op,
            # not an error — same skip rule as every node-targeted action
            if all(e == "*" or e in self.cluster.nodes for e in (src, dst)):
                rule = self.cluster.add_link_rule(src, dst, act)
                self._pending_heals.append(
                    (now + (a.dur_s or 2.0), "remove_rules", [rule]))
        elif a.kind == "flood":
            src_s, _, dst_s = a.arg.partition(">")
            src, dst = int(src_s), int(dst_s)
            if src in self.cluster.nodes and dst in self.cluster.nodes:
                rule = self.cluster.add_link_rule(src, dst, "flood~4")
                self._pending_heals.append(
                    (now + (a.dur_s or 1.0), "remove_rules", [rule]))
        elif a.kind == "join":
            self.cluster.join_node(statesync=False)
        elif a.kind == "join_statesync":
            self.cluster.join_node(statesync=True)
        elif a.kind == "power":
            idx_s, _, pow_s = a.arg.partition(":")
            idx = int(idx_s)
            if idx in self.cluster.nodes:
                self.cluster.promote(idx, int(pow_s))
        elif a.kind == "restart":
            idx = int(a.arg)
            if idx in self.cluster.nodes:
                self.cluster.restart_node(idx)
        elif a.kind == "leave":
            idx = int(a.arg)
            # never destroy genesis quorum: only drop a node whose power
            # the remaining set can lose (joiners, or <1/3 of total)
            if idx in self.cluster.nodes and not self._quorum_cut(
                    [[i for i in self.cluster.nodes if i != idx]]):
                self.cluster.remove_node(idx)
        elif a.kind == "evidence":
            idx = int(a.arg)
            if idx in self.cluster.nodes and self._byz_install_ok(idx):
                self.cluster.install_misbehavior(idx)
        elif a.kind == "byz":
            idx_s, _, spec = a.arg.partition(":")
            idx = int(idx_s)
            if idx in self.cluster.nodes and self._byz_install_ok(idx):
                self.cluster.install_byzantine(idx, spec or "double_prevote")
        elif a.kind == "bitrot":
            parts = a.arg.split(":")
            idx = int(parts[0])
            store = parts[1] if len(parts) > 1 else "block"
            mode = parts[2] if len(parts) > 2 else "bitrot"
            if idx in self.cluster.nodes:
                self._apply_bitrot(self.cluster.nodes[idx], store, mode)
        elif a.kind == "crash":
            parts = a.arg.split(":")
            tear = parts[1] if len(parts) > 1 else ""
            self._crash([int(parts[0])], a.dur_s, now, tear=tear)
        elif a.kind == "crashstorm":
            rng = random.Random(f"soak-crash:{self.seed}:{self.fired}")
            byz = getattr(self.cluster, "byzantine", set())
            pool = [i for i in sorted(self.cluster.nodes) if i not in byz]
            k = min(int(a.arg or "1"), max(len(pool) - 1, 0))
            self._crash(rng.sample(pool, k) if k else [], a.dur_s, now)
        elif a.kind == "skew":
            idx_s, _, secs = a.arg.partition(":")
            idx = int(idx_s)
            if idx in self.cluster.nodes:
                self.cluster.set_skew(idx, float(secs))
                if a.dur_s > 0:
                    self._pending_heals.append((now + a.dur_s, "unskew", idx))
        elif a.kind == "lightcrowd":
            crowd = LightCrowd(self.cluster, self.auditor,
                               n_clients=int(a.arg or "8"),
                               seed=self.seed + self.fired,
                               logger=self.logger)
            crowd.start()
            self._crowds.append(crowd)
            if a.dur_s > 0:
                self._pending_heals.append((now + a.dur_s, "crowd_stop",
                                            crowd))

    def _crash(self, victims: list[int], downtime: float, now: float,
               tear: str = "") -> None:
        """Power-loss hard-kill of ``victims`` — no stop(), no flushes, the
        durable home abandoned exactly as the crash left it — then, unless
        the downtime is NEGATIVE (machine lost forever), staggered reboots
        of new incarnations from those homes. Quorum arithmetic mirrors
        partitions: losing quorum with reboots pending is an EXPECTED
        stall (cleared when the last victim is back); losing it with a
        never-reboot kill is a liveness violation by design."""
        if not getattr(self.cluster, "durable", False):
            raise RuntimeError(
                "crash actions need a durable cluster "
                "(run_soak(durable=True) / TMTPU_SOAK_DURABLE=1)")
        victims = [i for i in victims if i in self.cluster.nodes]
        if not victims:
            return
        rebooting = downtime >= 0
        survivors = [i for i in self.cluster.nodes if i not in victims]
        armed = rebooting and self._quorum_cut([survivors])
        if armed:
            self.auditor.expect_stall(True)
        for n, idx in enumerate(victims):
            self.cluster.hard_kill(idx, tear=tear or None,
                                   seed=self.seed + self.fired)
            if rebooting:
                self._pending_heals.append(
                    (now + (downtime or 3.0) + 0.3 * n, "reboot",
                     (idx, armed and n == len(victims) - 1)))

    def _apply_bitrot(self, fn, store: str, mode: str) -> None:
        """At-rest corruption of one committed record on a live node, then
        a detection scrub whose repairs drain on the node's background
        repair worker (store/repair.py) — the perturbation the rest of the
        fault stack could not express: disk rot under traffic."""
        node = fn.node
        rng = random.Random(f"soak-bitrot:{self.seed}:{self.fired}")
        key = None
        if store == "state":
            db = node.state_store._db
            rows = [k for k, _ in db.iterator(b"validatorsKey:",
                                              b"validatorsKey;")]
            if rows:
                key = rng.choice(sorted(rows))
        else:
            from tendermint_tpu.store import block_store as bs_mod

            bs = node.block_store
            db = bs._db
            if bs.height > bs.base:
                h = rng.randrange(bs.base, bs.height)  # never the live tip
                key = rng.choice((bs_mod._meta_key(h), bs_mod._part_key(h, 0),
                                  bs_mod._seen_commit_key(h)))
                if db.get(key) is None:
                    key = bs_mod._meta_key(h)
        if key is None or db.get(key) is None:
            return  # nothing committed to rot yet; recorded as fired anyway
        faults.corrupt_db(db, key, mode=mode, seed=self.seed)
        node.scrubber().scrub(repairer=node.store_repairer, drain=False)

    def _drain_heals(self, now: float) -> None:
        for entry in list(self._pending_heals):
            t, what, payload = entry
            if now < t:
                continue
            self._pending_heals.remove(entry)
            try:
                if what == "heal":
                    self.cluster.heal()
                    self.auditor.expect_stall(False)
                elif what == "remove_rules":
                    # expire exactly the rules this fault installed:
                    # partition-safe (heal keeps link rules standing) and
                    # overlap-safe (other faults' rules stay live).
                    # drop/delay/dup/flood never sever links, so no relink
                    for rule in payload:
                        nemesis.remove_link(rule)
                elif what == "reboot":
                    idx, armed = payload
                    self.cluster.reboot(idx)
                    if armed:  # last quorum-restoring reboot of the crash
                        self.auditor.expect_stall(False)
                elif what == "unskew":
                    if payload in self.cluster.nodes:
                        self.cluster.set_skew(payload, 0.0)
                elif what == "crowd_stop":
                    payload.stop()
            except Exception as e:  # noqa: BLE001 - a failed relink is a
                # finding, not a crashed soak: record it and keep driving
                self.auditor._record("audit", f"{what} failed: {e}")
                if what == "heal" or (what == "reboot" and payload[1]):
                    self.auditor.expect_stall(False)

    # --- the run loop -------------------------------------------------------

    def run(self) -> SoakReport:
        rng = random.Random(f"soak-load:{self.seed}")
        pending = list(self.schedule.actions)
        t0 = time.monotonic()
        next_tx = 0.0
        self.auditor.start()
        try:
            while True:
                now = time.monotonic() - t0
                if now >= self.duration_s and not self._pending_heals:
                    break
                while pending and now >= pending[0].at_s:
                    a = pending.pop(0)
                    try:
                        self._apply(a, now)
                    except Exception as e:  # noqa: BLE001 - one impossible
                        # action (joiner before trust anchor, dead index)
                        # must not abort the soak; it IS recorded
                        self.auditor._record("audit",
                                             f"action {a.describe()} failed: {e}")
                self._drain_heals(now)
                if now >= next_tx:
                    next_tx = now + self.tx_interval_s
                    tx = b"soak%d=v%d" % (self.txs, rng.randrange(1 << 30))
                    if self.cluster.submit_tx(tx):
                        self.txs += 1
                time.sleep(0.05)
        finally:
            for crowd in self._crowds:
                crowd.stop()
            self.auditor.stop()
        # final synchronous drain + full-prefix audit (belt over the
        # incremental braces; also covers commits after the last sweep)
        try:
            self.auditor.sweep()
            self.cluster.audit_agreement()
        except AssertionError as e:
            self.auditor._record("audit", str(e))
        except Exception as e:  # noqa: BLE001 - teardown race
            self.auditor._record("audit", f"final audit errored: {e}")
        report = SoakReport(
            seed=self.seed, nodes=self.cluster.n_initial,
            topology=self.cluster.topology, duration_s=self.duration_s,
            schedule=self.schedule.describe(),
            heights=self.cluster.heights(),
            heights_audited=self.auditor.heights_audited,
            evidence_audited=self.auditor.evidence_audited,
            byzantine=sorted(getattr(self.cluster, "byzantine", ())),
            txs_submitted=self.txs, actions_fired=self.fired,
            violations=[str(v) for v in self.auditor.violations],
        )
        if self._crowds:
            crowds = [c.stats() for c in self._crowds]
            report.light = {
                "crowds": crowds,
                "queries": sum(c["queries"] for c in crowds),
                "served": sum(c["served"] for c in crowds),
                "refused": sum(c["refused"] for c in crowds),
                "answers_audited": self.auditor.light_answers_audited,
            }
        report.repro = repro_line(self.seed, self.cluster.n_initial,
                                  self.cluster.topology, self.duration_s,
                                  report.schedule,
                                  statesync=self.cluster.rpc_node >= 0,
                                  durable=getattr(self.cluster, "durable",
                                                  False))
        if not report.ok:
            print(report.repro)
        return report


def run_soak(root: str, seed: int = 1, nodes: int = DEFAULT_NODES,
             duration_s: float = DEFAULT_DURATION_S,
             topology: str = DEFAULT_TOPOLOGY, schedule_spec: str = "",
             statesync_ok: bool = False, durable: bool = False,
             liveness_budget_s: float = 30.0,
             tweak=None, logger=None) -> SoakReport:
    """Build a cluster, run one seeded soak, tear down, report.

    Env overrides (the repro-line knobs): ``TMTPU_SOAK_SEED``,
    ``TMTPU_SOAK_NODES``, ``TMTPU_SOAK_TOPOLOGY``,
    ``TMTPU_SOAK_DURATION_S``, ``TMTPU_SOAK_SCHEDULE``,
    ``TMTPU_SOAK_STATESYNC``, ``TMTPU_SOAK_DURABLE``. Durable mode gives
    every node an on-disk home that survives hard kills — required by
    (and implied in schedules containing) the crash/crashstorm actions."""
    seed = int(os.environ.get("TMTPU_SOAK_SEED", seed))
    nodes = int(os.environ.get("TMTPU_SOAK_NODES", nodes))
    topology = os.environ.get("TMTPU_SOAK_TOPOLOGY", topology)
    duration_s = float(os.environ.get("TMTPU_SOAK_DURATION_S", duration_s))
    schedule_spec = os.environ.get("TMTPU_SOAK_SCHEDULE", schedule_spec)
    statesync_ok = os.environ.get(
        "TMTPU_SOAK_STATESYNC", "1" if statesync_ok else "") == "1"
    durable = os.environ.get(
        "TMTPU_SOAK_DURABLE", "1" if durable else "") == "1"
    faults.configure([], seed=faults.REGISTRY.seed or 2026)
    schedule = (SoakSchedule.parse(schedule_spec) if schedule_spec
                else SoakSchedule.generate(seed, duration_s, nodes,
                                           statesync_ok=statesync_ok,
                                           durable=durable))
    # a replayed schedule that contains crash actions implies durable homes
    durable = durable or any(a.kind in _DURABLE_KINDS
                             for a in schedule.actions)
    cluster = Cluster(
        root, nodes, topology=topology, durable=durable,
        snapshot_interval=4 if statesync_ok else 0,
        rpc_node=0 if statesync_ok else -1, tweak=tweak,
        # per-node flight recorders feed the auditor's last-phase stall
        # annotations; default ON for soaks, TMTPU_TRACE=0 opts out
        trace=os.environ.get("TMTPU_TRACE", "1") != "0", logger=logger)
    cluster.start()
    try:
        driver = SoakDriver(cluster, schedule, seed, duration_s,
                            liveness_budget_s=liveness_budget_s,
                            logger=logger)
        return driver.run()
    finally:
        cluster.stop()
        nemesis.clear()


def main(argv=None) -> int:
    import argparse
    import json
    import tempfile
    from dataclasses import asdict

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--nodes", type=int, default=DEFAULT_NODES)
    ap.add_argument("--duration", type=float, default=DEFAULT_DURATION_S)
    ap.add_argument("--topology", default=DEFAULT_TOPOLOGY)
    ap.add_argument("--schedule", default="")
    ap.add_argument("--statesync", action="store_true")
    ap.add_argument("--durable", action="store_true",
                    help="on-disk node homes (enables crash/crashstorm)")
    args = ap.parse_args(argv)
    with tempfile.TemporaryDirectory(prefix="tmtpu-soak-") as root:
        report = run_soak(root, seed=args.seed, nodes=args.nodes,
                          duration_s=args.duration, topology=args.topology,
                          schedule_spec=args.schedule,
                          statesync_ok=args.statesync, durable=args.durable)
    print(json.dumps(asdict(report), indent=1, default=str))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
