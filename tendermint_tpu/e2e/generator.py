"""Deterministic random e2e manifest generator (reference:
test/e2e/generator/generate.go — the reference rolls random testnet
topologies from a seed and runs the whole matrix nightly; same idea here
over the dimensions this runner supports).

Every draw is derived from the seed, so a failing topology is reproducible
by number: `python -m tendermint_tpu.e2e.generator --seed 42 --count 8`
writes manifest JSON files; `generate(seed)` returns Manifest objects.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict

from tendermint_tpu.e2e.runner import Manifest, Perturbation, PowerChange

# Dimension tables (reference: generator/generate.go testnetCombinations).
_VALIDATORS = (2, 3, 4, 5)
_FASTSYNC = ("v0", "v0", "v1", "v2")  # v0 weighted: the default path
_PERTURB_ACTIONS = ("kill", "restart", "pause", "partition")
# Clock-skew dimension, seconds (negative = the node lives in the past).
_CLOCK_SKEWS = (-90, -30, 45, 120, 600)
# Light-client crowd sizes (docs/LIGHT.md light-serving dimension).
_LIGHT_CROWDS = (4, 8, 16)
# Byzantine behavior dimension (docs/BYZANTINE.md): derived from the
# authoritative consensus/misbehavior.py catalog (minus the `absent`
# alias) so a behavior added there enters the nightly matrix
# automatically; double_prevote double-weighted — it is the one that
# provokes the committed DuplicateVoteEvidence runner assertions key on.
from tendermint_tpu.consensus.misbehavior import BEHAVIORS as _MB_BEHAVIORS

_BYZ_BEHAVIORS = ("double_prevote",) + tuple(
    b for b in _MB_BEHAVIORS if b != "absent")


def generate_one(rng: random.Random, index: int = 0) -> Manifest:
    n_vals = rng.choice(_VALIDATORS)
    target = rng.randrange(8, 14)
    perts = []
    # Perturb at most floor((n-1)/3) nodes concurrently-ish: the net must
    # keep > 2/3 honest-and-up power to make progress while one node is
    # down, so small nets get at most one perturbation.
    for _ in range(rng.randrange(0, 2 if n_vals < 4 else 3)):
        action = rng.choice(_PERTURB_ACTIONS)
        node = rng.randrange(n_vals)
        groups = []
        if action == "partition":
            # nemesis-driven cut: isolate `node` (the runner installs the
            # symmetric cut over unsafe_nemesis and heals at revive time)
            groups = [[node], [i for i in range(n_vals) if i != node]]
        perts.append(Perturbation(
            node=node,
            action=action,
            at_height=rng.randrange(3, max(4, target - 3)),
            revive_after_s=round(rng.uniform(0.5, 2.0), 1),
            groups=groups,
        ))
    # Validator-power churn through the ABCI validator_updates path: roll
    # a mid-run power change on a third of manifests (never to 0 on tiny
    # sets — dropping a validator from a 2-set kills quorum outright).
    powers = []
    if rng.random() < 0.33:
        powers.append(PowerChange(
            node=rng.randrange(n_vals),
            power=rng.choice((5, 15, 20) if n_vals < 4 else (0, 5, 15, 20)),
            at_height=rng.randrange(3, max(4, target - 2)),
        ))
    # A byzantine node needs >= 4 validators (1 byzantine < 1/3 of 4);
    # roll it on a third of the big topologies, cycling the behavior
    # dimension so the nightly matrix walks the whole maverick catalog.
    byz = -1
    misbehavior = "double_prevote"
    if n_vals >= 4 and rng.random() < 0.33:
        byz = rng.randrange(n_vals)
        misbehavior = rng.choice(_BYZ_BEHAVIORS)
    # Clock-skew dimension: one node runs with its whole time plane offset
    # (TMTPU_CLOCK_SKEW_S). Needs >= 3 validators so the skewed timestamp
    # stays a sub-1/3 voice in the BFT-time weighted median.
    skewed = -1
    skew_s = 0.0
    if n_vals >= 3 and rng.random() < 0.25:
        skewed = rng.randrange(n_vals)
        skew_s = float(rng.choice(_CLOCK_SKEWS))
    # Light-serving dimension (docs/LIGHT.md): a quarter of manifests run
    # a gateway light-client crowd over the finished net's real RPC —
    # every verified answer is cross-checked against the committed chain.
    light_clients = rng.choice(_LIGHT_CROWDS) if rng.random() < 0.25 else 0
    return Manifest(
        validators=n_vals,
        chain_id=f"gen-{index}",
        target_height=target,
        load_txs=rng.randrange(5, 25),
        perturbations=perts,
        power_changes=powers,
        byzantine_node=byz,
        misbehavior=misbehavior,
        fastsync_version=rng.choice(_FASTSYNC),
        statesync_joiner=n_vals >= 3 and rng.random() < 0.25,
        skewed_node=skewed,
        clock_skew_s=skew_s,
        light_clients=light_clients,
    )


def generate(seed: int, count: int = 8) -> list[Manifest]:
    rng = random.Random(seed)
    return [generate_one(rng, i) for i in range(count)]


def main(argv=None) -> int:
    import argparse
    import os

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--count", type=int, default=8)
    ap.add_argument("--output", default="generated-manifests")
    args = ap.parse_args(argv)
    os.makedirs(args.output, exist_ok=True)
    for i, m in enumerate(generate(args.seed, args.count)):
        path = os.path.join(args.output, f"manifest-{args.seed}-{i}.json")
        with open(path, "w") as f:
            json.dump(asdict(m), f, indent=1)
        print(path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
