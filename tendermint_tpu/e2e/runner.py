"""Manifest-driven multi-process e2e testnet runner (reference: test/e2e/
runner/: stages setup/start/load/perturb/wait/test/stop; perturbations in
runner/perturb.go).

Each node is a REAL OS process (`python -m tendermint_tpu.cli start`) with
durable sqlite stores and a WAL, connected over real TCP — the in-process
harness can't prove crash recovery or process isolation; this can. A
manifest describes the topology and a perturbation schedule:

    Manifest(validators=4, target_height=12, load_txs=20,
             perturbations=[Perturbation(node=3, action="kill",
                                         at_height=5, revive_after_s=2)])

Actions (reference runner/perturb.go): kill (SIGKILL + restart),
restart (SIGTERM + restart), pause (SIGSTOP/SIGCONT), disconnect (SIGSTOP
without revive until revive_after_s).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field


# --- load-aware progress waiting (r4 verdict item 6) ------------------------
#
# Wall-clock deadlines flake on the 1-core CI host: any concurrent load
# stretches every stage uniformly, and a fixed budget ends up measuring the
# contention, not the testnet. These waits are PROGRESS-based instead: they
# fail only when the progress metric (a height) stalls for an idle budget
# that is scaled live by measured host contention (the bench's
# spin-calibration trick: a fixed CPU loop's elapsed time is the load
# factor). A hard cap bounds total runtime against genuine hangs.

_SPIN_BASELINE: float | None = None


def _spin_ms() -> float:
    t0 = time.monotonic()
    x = 0
    for i in range(400_000):
        x += i
    return (time.monotonic() - t0) * 1e3


def calibrate_spin() -> float:
    """Record (or improve) the quiet-host spin baseline."""
    global _SPIN_BASELINE
    best = min(_spin_ms() for _ in range(3))
    if _SPIN_BASELINE is None or best < _SPIN_BASELINE:
        _SPIN_BASELINE = best
    return _SPIN_BASELINE


def load_factor() -> float:
    if _SPIN_BASELINE is None:
        calibrate_spin()
    return min(max(_spin_ms() / _SPIN_BASELINE, 1.0), 8.0)


def wait_progress(value_fn, done_fn, idle_budget_s: float, hard_cap_s: float,
                  what: str, tick=None, poll_s: float = 0.3) -> None:
    """Wait until done_fn(value) holds. value_fn returns a monotonic
    progress metric; the wait fails only if the metric stalls for
    idle_budget_s * load_factor(), or after hard_cap_s total."""
    best = value_fn()
    start = last_progress = time.monotonic()
    while True:
        if tick is not None:
            tick()
        if done_fn(best):
            return
        now = time.monotonic()
        factor = load_factor()
        idle = idle_budget_s * factor
        if now - last_progress > idle:
            raise TimeoutError(
                f"{what}: no progress for {now - last_progress:.0f}s "
                f"(budget {idle:.0f}s at load factor {factor:.1f}); "
                f"value={best}")
        if now - start > hard_cap_s:
            raise TimeoutError(f"{what}: hard cap {hard_cap_s:.0f}s "
                               f"exceeded; value={best}")
        time.sleep(poll_s)
        v = value_fn()
        if v > best:
            best = v
            last_progress = time.monotonic()


@dataclass
class Perturbation:
    node: int
    action: str  # kill | restart | pause | partition | heal
    at_height: int
    revive_after_s: float = 1.0
    # partition only: groups of node INDICES, e.g. [[0, 1], [2, 3]];
    # omitted -> isolate `node` from everyone else. Installed symmetrically
    # on every running node via the unsafe_nemesis RPC and healed at
    # revive_after_s (or by an explicit heal perturbation).
    groups: list = field(default_factory=list)


@dataclass
class PowerChange:
    """A voting-power change driven through the app's ``val:`` tx (ABCI
    EndBlock validator_updates -> state/execution.py update_state): change
    validator `node`'s power to `power` once the net reaches `at_height`.
    The update lands in the validator set two heights after the tx commits."""

    node: int
    power: int
    at_height: int


@dataclass
class Manifest:
    """reference: test/e2e/pkg/manifest.go (subset)."""

    validators: int = 4
    chain_id: str = ""
    target_height: int = 10
    load_txs: int = 10
    starting_port: int = 0  # 0 -> pick a free range
    perturbations: list[Perturbation] = field(default_factory=list)
    power_changes: list[PowerChange] = field(default_factory=list)
    # Node index to run byzantine (reference: maverick nodes in e2e
    # manifests, pkg/manifest.go Misbehaviors), -1 = none. The byzantine
    # node runs `misbehavior` — any consensus/misbehavior.py behavior spec
    # (docs/BYZANTINE.md), rolled by the generator's behavior dimension —
    # via TMTPU_MISBEHAVIOR; honest >2/3 must keep committing (and, for
    # the double-vote behaviors, produce DuplicateVoteEvidence).
    byzantine_node: int = -1
    misbehavior: str = "double_prevote"
    # Fast-sync version for all nodes (reference: manifest fast_sync key).
    fastsync_version: str = "v0"
    # Add a post-start state-sync joiner node (reference: statesync nodes).
    statesync_joiner: bool = False
    # Clock-skew dimension (docs/SOAK.md): run `skewed_node`'s process with
    # TMTPU_CLOCK_SKEW_S=clock_skew_s so its entire time plane — proposal
    # timestamps, timeout ticker, evidence aging — is offset from the rest
    # of the net. BFT time (weighted median) must absorb a sub-1/3 skewed
    # voice: honest >2/3 keep committing and header times stay monotonic.
    # -1 = no skewed node.
    skewed_node: int = -1
    clock_skew_s: float = 0.0
    # Light-serving dimension (docs/LIGHT.md): after the perturbation
    # matrix settles, run this many concurrent light clients behind one
    # LightGateway over the net's real RPC and cross-check every VERIFIED
    # answer against the chain's committed block id. Refusals are fine
    # (refuse-over-lie is the gateway contract); a hash mismatch fails
    # the run. 0 = no light-serving stage.
    light_clients: int = 0

    @staticmethod
    def from_file(path: str) -> "Manifest":
        with open(path) as f:
            doc = json.load(f)
        perts = [Perturbation(**p) for p in doc.pop("perturbations", [])]
        powers = [PowerChange(**p) for p in doc.pop("power_changes", [])]
        return Manifest(perturbations=perts, power_changes=powers, **doc)


def _free_port_base(n_ports: int) -> int:
    """A base port such that [base, base+n_ports) all bind right now."""
    import random
    import socket

    rng = random.Random(os.getpid())
    for _ in range(50):
        base = rng.randrange(20000, 60000 - n_ports)
        socks = []
        try:
            for off in range(n_ports):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + off))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port range found")


class Runner:
    """reference: test/e2e/runner/main.go stage driver."""

    def __init__(self, manifest: Manifest, workdir: str, logger=None):
        self.m = manifest
        self.workdir = os.path.abspath(workdir)
        self.logger = logger
        self.procs: dict[int, subprocess.Popen | None] = {}
        self._paused: set[int] = set()
        if not self.m.starting_port:
            self.m.starting_port = _free_port_base(2 * (self.m.validators + 1))
        self.rpc_addrs = {
            i: f"http://127.0.0.1:{self.m.starting_port + 2 * i + 1}"
            for i in range(self.m.validators)
        }

    # --- stages -------------------------------------------------------------

    def setup(self) -> None:
        calibrate_spin()  # quiet-host baseline before the net loads the box
        from tendermint_tpu.cli.main import main as cli

        rc = cli(["testnet", "--v", str(self.m.validators),
                  "--output", self.workdir,
                  "--chain-id", self.m.chain_id or "e2e-chain",
                  "--starting-port", str(self.m.starting_port)])
        if rc != 0:
            raise RuntimeError("testnet setup failed")
        # default_config already uses the durable sqlite backend, so
        # kill/restart exercises real recovery; nothing to patch.
        from tendermint_tpu.config.config import default_config
        from tendermint_tpu.config.toml import (
            load_toml_into, write_config_toml)

        for i in range(self.m.validators):
            home = os.path.join(self.workdir, f"node{i}")
            path = os.path.join(home, "config", "config.toml")
            cfg = load_toml_into(default_config().set_root(home), path)
            cfg.fastsync.version = self.m.fastsync_version
            # localhost chaos harness: the partition/heal perturbations
            # drive each node's nemesis plane over the unsafe RPC route
            cfg.rpc.unsafe = True
            write_config_toml(cfg, path)

    def _spawn(self, i: int) -> subprocess.Popen:
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "TM_TPU_DISABLE_BATCH": os.environ.get("TM_TPU_DISABLE_BATCH", ""),
               # serving nodes take app snapshots so late joiners can
               # state-sync in (reference e2e: snapshot_interval manifest key)
               "TMTPU_KVSTORE_SNAPSHOT_INTERVAL":
                   os.environ.get("TMTPU_KVSTORE_SNAPSHOT_INTERVAL", "4")}
        if i == self.m.byzantine_node:
            env["TMTPU_MISBEHAVIOR"] = self.m.misbehavior
        if i == self.m.skewed_node and self.m.clock_skew_s:
            env["TMTPU_CLOCK_SKEW_S"] = str(self.m.clock_skew_s)
        log = open(os.path.join(self.workdir, f"node{i}.log"), "ab")
        return subprocess.Popen(
            [sys.executable, "-m", "tendermint_tpu.cli",
             "--home", os.path.join(self.workdir, f"node{i}"), "start"],
            stdout=log, stderr=log, env=env)

    def start(self) -> None:
        for i in range(self.m.validators):
            self.procs[i] = self._spawn(i)

    def _load_targets(self) -> list[int]:
        """Round-robin universe for client traffic: every node with an RPC
        address, INCLUDING post-start joiners (a statesync-joined node that
        never receives client load is a dead weight the old
        `attempt % validators` cursor silently created)."""
        return sorted(self.rpc_addrs)

    def load(self) -> None:
        """Submit load_txs round-robin over the nodes' RPC (reference:
        runner/load.go)."""
        sent = 0
        attempt = 0
        deadline = time.monotonic() + 60
        while sent < self.m.load_txs and time.monotonic() < deadline:
            targets = self._load_targets()
            node = targets[attempt % len(targets)]
            attempt += 1
            if node in self._paused or self.procs.get(node) is None:
                if attempt % len(targets) == 0:
                    time.sleep(0.05)  # every node skipped: don't spin hot
                continue
            tx = b"e2e%d=v%d" % (sent, sent)
            try:
                self._rpc(node, "broadcast_tx_sync",
                          {"tx": __import__("base64").b64encode(tx).decode()})
                sent += 1
            except Exception:  # noqa: BLE001 - node may still be booting
                time.sleep(0.3)

    def load_report(self, window_s: float = 20.0) -> dict:
        """Timed load window -> throughput report (reference:
        test/loadtime/ + the QA tables in docs/qa/v034/README.md; the
        anchors there: 19.5 blocks/min, ~200-339 tx/s on 200 4-core
        droplets — this is a 1-core localnet, so the numbers are recorded
        for trend, not for parity with that hardware).

        Returns {window_s, blocks, blocks_per_min, txs_committed, tx_per_s,
        first_height, last_height}."""
        import base64

        start_h = self.max_height()
        deadline = time.monotonic() + window_s
        sent = 0
        attempt = 0  # round-robin cursor: advances even past dead/erroring
        while time.monotonic() < deadline:  # nodes, so one sick node can't
            targets = self._load_targets()  # pin the whole window
            node = targets[attempt % len(targets)]
            attempt += 1
            if node in self._paused or self.procs.get(node) is None:
                if attempt % len(targets) == 0:
                    time.sleep(0.05)  # every node skipped: don't spin hot
                continue
            tx = b"load%d=v%d" % (sent, sent)
            try:
                self._rpc(node, "broadcast_tx_sync",
                          {"tx": base64.b64encode(tx).decode()})
                sent += 1
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
        end_h = self.max_height()
        txs = 0
        for h in range(start_h + 1, end_h + 1):
            try:
                b = self._rpc(0, "block", {"height": str(h)})
                txs += len(b["block"]["data"]["txs"] or [])
            except Exception:  # noqa: BLE001
                continue
        blocks = end_h - start_h
        return dict(window_s=window_s, blocks=blocks,
                    blocks_per_min=round(blocks * 60.0 / window_s, 1),
                    txs_sent=sent, txs_committed=txs,
                    tx_per_s=round(txs / window_s, 1),
                    first_height=start_h, last_height=end_h)

    def _progress_wait(self, value_fn, done_fn, idle_budget_s: float,
                       hard_cap_s: float, what: str, tick=None) -> None:
        wait_progress(value_fn, done_fn, idle_budget_s, hard_cap_s, what,
                      tick=tick)

    def perturb_and_wait(self, timeout_s: float = 180.0) -> None:
        """Run the perturbation schedule while waiting for target_height
        (reference: runner/perturb.go + wait.go). timeout_s is the IDLE
        budget basis: the wait fails on a height stall of timeout_s/3
        (load-scaled), or a hard cap of 4x timeout_s."""
        pending = sorted(self.m.perturbations, key=lambda p: p.at_height)
        powers = sorted(self.m.power_changes, key=lambda p: p.at_height)
        revive_at: list[tuple[float, int, str]] = []

        def tick():
            h = self.max_height()
            while pending and h >= pending[0].at_height:
                self._apply(pending.pop(0), revive_at)
            while powers and h >= powers[0].at_height:
                self._apply_power_change(powers.pop(0))
            now = time.monotonic()
            for t, node, action in list(revive_at):
                if now >= t:
                    revive_at.remove((t, node, action))
                    self._revive(node, action)

        self._progress_wait(
            self.max_height,
            lambda h: (h >= self.m.target_height and not pending
                       and not powers and not revive_at),
            idle_budget_s=timeout_s / 3.0, hard_cap_s=timeout_s * 4.0,
            what=f"testnet reaching height {self.m.target_height}",
            tick=tick)

    def _apply(self, p: Perturbation, revive_at: list) -> None:
        if p.action == "partition":
            groups = p.groups or [[p.node],
                                  [i for i in range(self.m.validators)
                                   if i != p.node]]
            self.partition(groups)
            revive_at.append((time.monotonic() + p.revive_after_s,
                              p.node, p.action))
            return
        if p.action == "heal":
            self.heal()
            return
        proc = self.procs.get(p.node)
        if proc is None:
            return
        if p.action == "kill":
            proc.kill()
            proc.wait()
            self.procs[p.node] = None
        elif p.action == "restart":
            proc.terminate()
            proc.wait()
            self.procs[p.node] = None
        elif p.action == "pause":
            proc.send_signal(signal.SIGSTOP)
            self._paused.add(p.node)
        revive_at.append((time.monotonic() + p.revive_after_s, p.node, p.action))

    def _apply_power_change(self, pc: PowerChange) -> None:
        """Broadcast the app's ``val:`` tx changing validator `pc.node`'s
        power (pubkey from the shared genesis doc). Best effort over every
        reachable node: a power change racing a perturbation must not kill
        the schedule."""
        import base64

        from tendermint_tpu.abci.kvstore import KVStoreApplication
        from tendermint_tpu.types.genesis import GenesisDoc

        gen = GenesisDoc.from_file(
            os.path.join(self.workdir, "node0", "config", "genesis.json"))
        if not 0 <= pc.node < len(gen.validators):
            return
        pub = gen.validators[pc.node].pub_key
        tx = KVStoreApplication.make_val_tx(pub.bytes(), pc.power)
        for i in self._load_targets():
            if i in self._paused or self.procs.get(i) is None:
                continue
            try:
                self._rpc(i, "broadcast_tx_sync",
                          {"tx": base64.b64encode(tx).decode()})
                return
            except Exception:  # noqa: BLE001 - next node
                continue

    def _revive(self, node: int, action: str) -> None:
        if action in ("kill", "restart"):
            self.procs[node] = self._spawn(node)
        elif action == "pause":
            self.procs[node].send_signal(signal.SIGCONT)
            self._paused.discard(node)
        elif action == "partition":
            self.heal()

    # --- nemesis control (reference: runner/perturb.go drives docker
    # network disconnects; here each node's link plane over unsafe RPC) ----

    def node_ids(self) -> dict[int, str]:
        """node index -> p2p node id, from each node's status RPC."""
        ids = {}
        for i in list(self.rpc_addrs):
            try:
                st = self._rpc(i, "status", {})
                ids[i] = st["node_info"]["id"]
            except Exception:  # noqa: BLE001 - dead/paused node
                continue
        return ids

    def _nemesis_all(self, params: dict) -> None:
        """Install the same nemesis command on every reachable node — a
        partition is a property of the NETWORK, so every member must agree
        on the cut for it to be symmetric."""
        for i in list(self.rpc_addrs):
            if i in self._paused or self.procs.get(i) is None:
                continue
            try:
                self._rpc(i, "unsafe_nemesis", params)
            except Exception:  # noqa: BLE001 - a dead node needs no cut
                continue

    def partition(self, groups: list) -> None:
        """Cut the network into groups of node INDICES (e.g. [[0,1],[2,3]]):
        messages and dials between different groups are dropped on every
        node until heal()."""
        ids = self.node_ids()
        id_groups = [[ids[i] for i in g if i in ids] for g in groups]
        id_groups = [g for g in id_groups if g]
        self._nemesis_all({"partition": id_groups})

    def heal(self) -> None:
        """Remove the partition on every node (persistent-peer backoff is
        kicked node-side so links re-establish promptly)."""
        self._nemesis_all({"heal": True})

    # --- checks (reference: test/e2e/tests/) --------------------------------

    def max_height(self) -> int:
        best = 0
        for i in list(self.rpc_addrs):
            try:
                st = self._rpc(i, "status", {})
                best = max(best, int(st["sync_info"]["latest_block_height"]))
            except Exception:  # noqa: BLE001
                continue
        return best

    def assert_consistent(self, height: int) -> None:
        """All reachable nodes agree on the block hash at `height`."""
        hashes = {}
        for i in list(self.rpc_addrs):
            try:
                b = self._rpc(i, "block", {"height": str(height)})
                hashes[i] = b["block_id"]["hash"]
            except Exception:  # noqa: BLE001
                continue
        assert len(hashes) >= 2, f"too few reachable nodes: {hashes}"
        assert len(set(hashes.values())) == 1, f"fork detected: {hashes}"

    def audit_agreement(self, min_height: int = 1) -> int:
        """The BFT safety audit: block-hash agreement across EVERY
        committed height on all reachable nodes, not one sampled height —
        a fork at any height anywhere is a safety violation the
        single-height check can miss (nodes can agree at h and have forked
        at h-3). A node that hasn't committed a height yet simply doesn't
        vote for it. Returns the number of heights audited; raises
        AssertionError with the full per-node hash map on any fork."""
        max_h = self.max_height()
        audited = 0
        for h in range(min_height, max_h + 1):
            hashes = {}
            for i in list(self.rpc_addrs):
                try:
                    b = self._rpc(i, "block", {"height": str(h)})
                    hashes[i] = b["block_id"]["hash"]
                except Exception:  # noqa: BLE001 - not committed there yet
                    continue
            if len(hashes) >= 2:
                audited += 1
                assert len(set(hashes.values())) == 1, (
                    f"fork at height {h}: {hashes}")
        assert audited >= 1, f"no height auditable across nodes (max {max_h})"
        return audited

    def min_height(self) -> int:
        """Lowest latest-height over the reachable nodes (−1: none)."""
        worst = None
        for i in list(self.rpc_addrs):
            try:
                st = self._rpc(i, "status", {})
                h = int(st["sync_info"]["latest_block_height"])
                worst = h if worst is None else min(worst, h)
            except Exception:  # noqa: BLE001
                continue
        return -1 if worst is None else worst

    def assert_liveness(self, delta: int = 2, within_s: float = 30.0) -> None:
        """Post-heal liveness bound: every node catches up to within
        `delta` heights of the max height within `within_s` (load-scaled
        idle budget; hard cap 4x)."""
        self._progress_wait(
            self.min_height,
            lambda _h: self.min_height() >= self.max_height() - delta,
            idle_budget_s=within_s, hard_cap_s=within_s * 4.0,
            what=f"all nodes within {delta} heights of the tip")

    def light_crowd_report(self, n_clients: int,
                           queries_each: int = 6) -> dict:
        """``n_clients`` concurrent light clients behind one LightGateway
        over the net's real RPC (docs/LIGHT.md): node0 is the primary,
        the other reachable nodes witnesses/spares, the trust anchor is
        the earliest still-in-trust-period header. Each client hammers
        seeded height queries; every VERIFIED answer is cross-checked
        against the committed block id node0 reports. Refusals are
        acceptable — a mismatch means the gateway served a wrong answer
        and fails the run."""
        import random
        import threading

        from tendermint_tpu.light.client import TrustOptions
        from tendermint_tpu.light.gateway import LightGateway
        from tendermint_tpu.light.provider import HTTPProvider
        from tendermint_tpu.light.store import DBStore
        from tendermint_tpu.light.verifier import header_expired
        from tendermint_tpu.store.db import MemDB
        from tendermint_tpu.types.ttime import Time

        chain_id = self.m.chain_id or "e2e-chain"
        alive = []
        for i in sorted(self.rpc_addrs):
            try:
                self._rpc(i, "status", {})
            except Exception:  # noqa: BLE001 - a down node can't serve
                continue
            alive.append(i)
        assert alive, "no reachable RPC node to serve light clients"
        alive = alive[:4]
        providers = [HTTPProvider(chain_id, self.rpc_addrs[i])
                     for i in alive]
        period_s = 168 * 3600
        anchor = providers[0].light_block(0)
        now = Time.now()
        for h in range(1, min(anchor.height, 17)):
            lb = providers[0].light_block(h)
            if not header_expired(lb.signed_header, period_s, now):
                anchor = lb
                break
        gw = LightGateway(
            chain_id,
            TrustOptions(period_s=period_s, height=anchor.height,
                         hash=anchor.hash()),
            providers, DBStore(MemDB(), chain_id),
            provider_names=[f"node{i}" for i in alive])
        tip = max(self.max_height(), 1)
        stats = {"clients": n_clients, "queries": 0, "served": 0,
                 "refused": 0, "mismatches": []}
        mtx = threading.Lock()

        def client(c: int) -> None:
            rng = random.Random(f"light:{self.m.chain_id}:{c}")
            for _ in range(queries_each):
                height = rng.randint(1, tip)
                try:
                    lb, _verdict = gw.serve_light_block(height)
                except Exception:  # noqa: BLE001 - typed refusal, not a lie
                    with mtx:
                        stats["queries"] += 1
                        stats["refused"] += 1
                    continue
                try:
                    want = self._rpc(alive[0], "block",
                                     {"height": str(lb.height)})
                    want_hash = want["block_id"]["hash"].lower()
                except Exception:  # noqa: BLE001 - chain check unavailable
                    want_hash = None
                with mtx:
                    stats["queries"] += 1
                    stats["served"] += 1
                    if (want_hash is not None
                            and lb.hash().hex().lower() != want_hash):
                        stats["mismatches"].append(lb.height)

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(n_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60.0)
        assert stats["served"] > 0, f"crowd never got an answer: {stats}"
        assert not stats["mismatches"], (
            f"gateway served wrong answers at heights {stats['mismatches']}")
        stats["gateway"] = gw.describe()["counters"]
        return stats

    def join_statesync_node(self, timeout_s: float = 120.0) -> int:
        """Spawn a NEW non-validator node that joins the live net via state
        sync (snapshot bootstrap + light-client trust through node0's RPC),
        then fast-syncs to the tip (reference: test/e2e 'stateSync' node
        perturbation). Returns the joiner's node index."""
        import shutil

        from tendermint_tpu.cli.main import _ensure_dirs, default_config
        from tendermint_tpu.config.toml import write_config_toml

        idx = self.m.validators  # next slot
        home = os.path.join(self.workdir, f"node{idx}")
        _ensure_dirs(home)
        # same genesis as the net
        shutil.copy(os.path.join(self.workdir, "node0", "config", "genesis.json"),
                    os.path.join(home, "config", "genesis.json"))
        # trust anchor from node0 (height 2 hash via RPC)
        meta = self._rpc(0, "block", {"height": "2"})
        trust_hash = meta["block_id"]["hash"]

        cfg = default_config().set_root(home)
        base_port = self.m.starting_port + 2 * idx
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_port}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_port + 1}"
        cfg.p2p.pex = False
        peers = []
        for i in range(self.m.validators):
            try:
                st = self._rpc(i, "status", {})
                peers.append(f"{st['node_info']['id']}@127.0.0.1:"
                             f"{self.m.starting_port + 2 * i}")
            except Exception:  # noqa: BLE001
                continue
        cfg.p2p.persistent_peers = ",".join(peers)
        cfg.base.fast_sync_mode = True
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = (self.rpc_addrs[0],)
        cfg.statesync.trust_height = 2
        cfg.statesync.trust_hash = trust_hash.lower()
        cfg.statesync.trust_period_s = 10 * 365 * 24 * 3600.0
        cfg.statesync.discovery_time_s = 1.0
        write_config_toml(cfg, os.path.join(home, "config", "config.toml"))

        self.rpc_addrs[idx] = f"http://127.0.0.1:{base_port + 1}"
        self.procs[idx] = self._spawn(idx)

        def joiner_height() -> int:
            try:
                st = self._rpc(idx, "status", {})
                return int(st["sync_info"]["latest_block_height"])
            except Exception:  # noqa: BLE001
                return -1

        def synced(_h) -> bool:
            try:
                st = self._rpc(idx, "status", {})
                return (int(st["sync_info"]["latest_block_height"])
                        >= self.m.target_height
                        and int(st["sync_info"]["earliest_block_height"]) > 1)
            except Exception:  # noqa: BLE001
                return False

        try:
            # idle basis timeout_s/2: the joiner pays a cold JAX import
            # before its RPC even answers (first "progress" is -1 -> 0),
            # which the load factor stretches on a contended host
            self._progress_wait(joiner_height, synced,
                                idle_budget_s=timeout_s / 2.0,
                                hard_cap_s=timeout_s * 4.0,
                                what="state-sync joiner reaching the tip")
            return idx
        except TimeoutError as e:
            timeout_msg = str(e)
        tail = ""
        try:
            with open(os.path.join(self.workdir, f"node{idx}.log"), "rb") as fh:
                fh.seek(0, os.SEEK_END)
                fh.seek(max(0, fh.tell() - 4096))
                raw = fh.read().decode("utf-8", "replace")
            tail = "\n".join(raw.splitlines()[-12:])
        except OSError:
            pass
        raise TimeoutError(
            f"joined node never state-synced to the tip ({timeout_msg}); "
            "joiner log tail:\n" + tail)

    def stop(self) -> None:
        for i, proc in self.procs.items():
            if proc is None:
                continue
            if i in self._paused:
                proc.send_signal(signal.SIGCONT)
            proc.terminate()
        for proc in self.procs.values():
            if proc is not None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()

    def _rpc(self, node: int, method: str, params: dict):
        body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                           "params": params}).encode()
        with urllib.request.urlopen(urllib.request.Request(
                self.rpc_addrs[node], data=body,
                headers={"Content-Type": "application/json"}), timeout=5) as r:
            doc = json.loads(r.read())
        if doc.get("error"):
            raise RuntimeError(doc["error"])
        return doc["result"]


def run_manifest(manifest: Manifest, workdir: str,
                 with_load_report: bool = False) -> dict:
    """All stages end to end (reference: runner/main.go). Returns a report
    dict (throughput numbers when with_load_report)."""
    r = Runner(manifest, workdir)
    r.setup()
    r.start()
    report: dict = {}
    try:
        r.load()
        r.perturb_and_wait()
        # full-prefix safety audit: every crash/pause/partition matrix run
        # gets fork detection at EVERY committed height, not one sample
        audited = r.audit_agreement()
        if with_load_report:
            report = r.load_report()
        report["heights_audited"] = audited
        if manifest.light_clients:
            report["light"] = r.light_crowd_report(manifest.light_clients)
        if manifest.statesync_joiner:
            report["joiner_index"] = r.join_statesync_node()
    finally:
        r.stop()
    return report
