"""Hour-scale soak campaigns with minimized repros (docs/SOAK.md §campaigns;
reference: the nightly e2e rotation of test/e2e/ — but one budgeted RUN
composing seeded phases over the ENTIRE adversary vocabulary, emitting a
tracked coverage artifact, and delta-debugging any failure down to a
minimal replayable schedule).

A campaign is a sequence of independent soak PHASES under one wall-clock
budget (``TMTPU_CAMPAIGN_BUDGET_S``): each phase builds a fresh durable
cluster, runs one seeded :func:`~tendermint_tpu.e2e.soak.run_soak`-style
schedule under the continuous auditor, and tears down. Phase seeds derive
from the campaign seed, so the whole campaign replays from ONE knob set —
the phase boundary is also the isolation boundary: a violation is
attributed to exactly one phase schedule, which is what makes the
minimizer's job finite.

**Coverage census.** The campaign tracks which action kinds its schedules
have composed so far and biases later phases toward the uncovered rest of
the vocabulary (seeded gap-fill injection, so the bias is replayable):
a budget long enough to run a handful of phases provably exercises every
adversary plane — partitions, link faults, floods, churn, power changes,
equivocation, byzantine roles, bit rot, power-loss crashes, crash storms,
and clock skew — and the emitted artifact proves it with per-kind counts.

**The artifact** (``SOAK_r01.json`` at the repo root; schema below) is the
campaign's durable output: coverage census, per-phase stats, commit and
audit totals, and — on failure — the violation list with phase
attribution plus the auto-minimized repro line::

    {"version": 1, "seed": ..., "budget_s": ..., "elapsed_s": ...,
     "nodes": ..., "phases": [{"phase": 0, "seed": ..., "schedule": ...,
     "duration_s": ..., "max_height": ..., "heights_audited": ...,
     "txs_submitted": ..., "actions_fired": ..., "violations": [...]}],
     "coverage": {"partition": 2, "crash": 1, ...},
     "stats": {"heights_audited": ..., "txs_submitted": ...,
     "actions_fired": ..., "max_height": ...},
     "violations": [{"phase": 0, "kind": "liveness", "detail": ...,
     "last_phase": {"1": "consensus.precommit(h4)", ...}}],
     "repro": "TMTPU_SOAK_REPRO: ...", "minimized_repro": "..."}

**Repro minimization.** On the first violating phase the campaign stops
and delta-debugs (classic ddmin over the ``;``-separated schedule
entries): seeded subsets of the failing schedule re-run against the
recorded violation signature (the violation KIND) until no strictly
smaller subset still reproduces it. The result is printed and recorded as
a one-line ``TMTPU_SOAK_REPRO`` an engineer replays directly — a
ten-entry storm schedule that fails because one never-rebooted quorum
crash minimizes to that single crash entry.

Knobs (all in docs/CONFIG.md): ``TMTPU_CAMPAIGN_SEED``,
``TMTPU_CAMPAIGN_BUDGET_S``, ``TMTPU_CAMPAIGN_PHASE_S``,
``TMTPU_CAMPAIGN_NODES``, ``TMTPU_CAMPAIGN_OUT``,
``TMTPU_CAMPAIGN_MINIMIZE``. The campaign deliberately IGNORES the
soak env overrides (``TMTPU_SOAK_SCHEDULE`` and friends) — phase
schedules are the campaign's to derive; the soak knobs configure
single soaks.
"""

from __future__ import annotations

import json
import os
import random
import re
import time

from tendermint_tpu.e2e.fabric import Cluster
from tendermint_tpu.e2e.soak import (SoakAction, SoakDriver, SoakSchedule,
                                     repro_line)
from tendermint_tpu.utils import faults, nemesis

SCHEMA_VERSION = 1
DEFAULT_BUDGET_S = 120.0
DEFAULT_PHASE_S = 25.0
DEFAULT_NODES = 6

# the vocabulary a campaign drives coverage over: every soak kind that
# composes against a fixed-size durable cluster (leave shrinks the
# validator set for good and join_statesync needs the rpc+snapshot
# serving config, so both stay opt-in via explicit phase schedules)
VOCABULARY = ("partition", "linkfault", "flood", "join", "power",
              "restart", "evidence", "byz", "bitrot", "crash",
              "crashstorm", "skew")


def _violation_kind(v: str) -> str:
    """``"[liveness @12.3s] detail"`` -> ``"liveness"`` (the minimizer's
    failure signature; Violation.__str__ is the only wire format the
    report keeps)."""
    v = str(v)
    if v.startswith("["):
        return v[1:].split("@")[0].strip()
    return "unknown"


def _last_phases(v: str) -> dict[str, str]:
    """Pull the flight-recorder attribution out of a violation's
    ``[lagging: node 1@h0 last_phase=consensus.precommit(h4), ...]``
    suffix into ``{node: phase}`` — the artifact consumer (and the
    minimizer's human reader) gets WHERE each lagging node was stuck as
    structured data instead of re-parsing the detail string."""
    out: dict[str, str] = {}
    for m in re.finditer(r"node (\d+)@h\d+ last_phase=([^,\]]+)", str(v)):
        out[m.group(1)] = m.group(2).strip()
    return out


def _gap_action(kind: str, at_s: float, target: int) -> SoakAction | None:
    """A deterministic single action of ``kind`` for coverage gap-fill.
    The injected crash always tears the WAL tail — the campaign's
    guarantee that torn-tail recovery is exercised, not left to the
    generator's coin."""
    t = round(at_s, 1)
    if kind == "partition":
        return SoakAction(t, kind, f"{target}|rest", 1.5)
    if kind == "linkfault":
        return SoakAction(t, kind, f"*>{target}:drop%0.5", 1.5)
    if kind == "flood":
        return SoakAction(t, kind, f"0>{target}", 1.0)
    if kind == "join":
        return SoakAction(t, kind)
    if kind == "power":
        return SoakAction(t, kind, f"{target}:15")
    if kind == "restart":
        return SoakAction(t, kind, str(target))
    if kind == "evidence":
        return SoakAction(t, kind, str(target))
    if kind == "byz":
        return SoakAction(t, kind, f"{target}:double_prevote")
    if kind == "bitrot":
        return SoakAction(t, kind, f"{target}:block:bitrot")
    if kind == "crash":
        return SoakAction(t, kind, f"{target}:torn", 2.0)
    if kind == "crashstorm":
        return SoakAction(t, kind, "1", 2.0)
    if kind == "skew":
        return SoakAction(t, kind, f"{target}:120", 5.0)
    return None


def fill_gaps(schedule: SoakSchedule, covered, duration_s: float,
              seed: int, nodes: int, max_inject: int = 3) -> SoakSchedule:
    """Bias a generated phase toward the campaign's uncovered vocabulary:
    inject up to ``max_inject`` seeded actions for kinds neither covered
    by an earlier phase nor present in this schedule. Deterministic in
    (seed, covered): a replayed campaign re-derives the same census at
    each phase boundary and therefore the same injections."""
    have = set(covered) | {a.kind for a in schedule.actions}
    missing = [k for k in VOCABULARY if k not in have][:max_inject]
    if not missing:
        return schedule
    rng = random.Random(f"campaign-gaps:{seed}")
    actions = list(schedule.actions)
    for i, kind in enumerate(missing):
        at = duration_s * (0.2 + 0.55 * (i + 1) / (len(missing) + 1))
        a = _gap_action(kind, at, rng.randrange(1, nodes))
        if a is not None:
            actions.append(a)
    return SoakSchedule(actions)


# --- repro minimization (ddmin) ----------------------------------------------


def minimize(entries: list[str], run_fn, max_runs: int = 24):
    """Classic delta debugging over schedule entries: find a 1-minimal
    subset for which ``run_fn(subset) -> True`` (the failure signature
    still reproduces). ``run_fn`` owns re-running the soak — injected so
    the algorithm is unit-testable without clusters — and the run budget
    is capped: each probe is a full seeded soak, so an un-capped ddmin on
    a long schedule could cost more than the campaign it serves. Returns
    ``(subset, runs_used)``; on a cap hit the best-so-far subset (always
    still failing) is returned."""
    runs = 0

    def probe(subset: list[str]) -> bool:
        nonlocal runs
        if runs >= max_runs:
            return False
        runs += 1
        return bool(run_fn(subset))

    cur = list(entries)
    n = 2
    while len(cur) >= 2 and runs < max_runs:
        chunk = max(1, (len(cur) + n - 1) // n)
        subsets = [cur[i:i + chunk] for i in range(0, len(cur), chunk)]
        reduced = False
        for i in range(len(subsets)):
            comp = [e for j, s in enumerate(subsets) if j != i for e in s]
            if comp and len(comp) < len(cur) and probe(comp):
                cur = comp
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(cur):
                break
            n = min(len(cur), n * 2)
    return cur, runs


# --- the campaign loop --------------------------------------------------------


def _run_phase(root: str, spec: str, seed: int, nodes: int, topology: str,
               duration_s: float, liveness_budget_s: float, logger=None):
    """One isolated phase: fresh durable cluster, one schedule, the
    continuous auditor, full teardown. Bypasses run_soak's env overrides
    on purpose (module docstring: the campaign owns its phase knobs)."""
    os.makedirs(root, exist_ok=True)
    faults.configure([], seed=faults.REGISTRY.seed or 2026)
    cluster = Cluster(
        root, nodes, topology=topology, durable=True,
        trace=os.environ.get("TMTPU_TRACE", "1") != "0", logger=logger)
    cluster.start()
    try:
        driver = SoakDriver(cluster, SoakSchedule.parse(spec), seed,
                            duration_s, liveness_budget_s=liveness_budget_s,
                            logger=logger)
        return driver.run()
    finally:
        cluster.stop()
        nemesis.clear()


def run_campaign(root: str, seed: int = 1, budget_s: float = DEFAULT_BUDGET_S,
                 phase_s: float = DEFAULT_PHASE_S, nodes: int = DEFAULT_NODES,
                 topology: str = "full", out: str = "",
                 minimize_on_violation: bool = True,
                 max_minimize_runs: int = 8,
                 liveness_budget_s: float = 30.0,
                 phase_specs: list[str] | None = None,
                 logger=None) -> dict:
    """Run seeded soak phases until the budget is spent (always at least
    one), stop at the first violating phase, minimize its schedule, and
    return (and optionally write) the campaign artifact.

    ``phase_specs`` pins explicit phase schedules (cycled) instead of
    seeded generation — the deterministic form CI stages use; generation
    plus gap-fill is the hour-scale soak form. Env overrides:
    ``TMTPU_CAMPAIGN_SEED``, ``TMTPU_CAMPAIGN_BUDGET_S``,
    ``TMTPU_CAMPAIGN_PHASE_S``, ``TMTPU_CAMPAIGN_NODES``,
    ``TMTPU_CAMPAIGN_OUT``, ``TMTPU_CAMPAIGN_MINIMIZE``."""
    seed = int(os.environ.get("TMTPU_CAMPAIGN_SEED", seed))
    budget_s = float(os.environ.get("TMTPU_CAMPAIGN_BUDGET_S", budget_s))
    phase_s = float(os.environ.get("TMTPU_CAMPAIGN_PHASE_S", phase_s))
    nodes = int(os.environ.get("TMTPU_CAMPAIGN_NODES", nodes))
    out = os.environ.get("TMTPU_CAMPAIGN_OUT", out)
    minimize_on_violation = os.environ.get(
        "TMTPU_CAMPAIGN_MINIMIZE",
        "1" if minimize_on_violation else "") == "1"
    t0 = time.monotonic()
    phases: list[dict] = []
    coverage: dict[str, int] = {}
    violations: list[dict] = []
    repro = minimized = ""
    i = 0
    while True:
        elapsed = time.monotonic() - t0
        if i > 0 and budget_s - elapsed < phase_s * 0.5:
            break  # not enough budget for a meaningful next phase
        dur = max(8.0, min(phase_s, budget_s - elapsed if i else phase_s))
        phase_seed = seed * 1000 + i
        if phase_specs:
            spec = phase_specs[i % len(phase_specs)]
        else:
            sched = SoakSchedule.generate(phase_seed, dur, nodes,
                                          durable=True)
            spec = fill_gaps(sched, coverage, dur, phase_seed,
                             nodes).describe()
        if logger:
            logger.info("campaign phase", phase=i, schedule=spec)
        p0 = time.monotonic()
        rep = _run_phase(os.path.join(root, f"phase_{i:02d}"), spec,
                         phase_seed, nodes, topology, dur,
                         liveness_budget_s, logger=logger)
        for a in SoakSchedule.parse(spec).actions:
            coverage[a.kind] = coverage.get(a.kind, 0) + 1
        phases.append({
            "phase": i, "seed": phase_seed, "schedule": spec,
            "duration_s": dur, "elapsed_s": round(time.monotonic() - p0, 1),
            "max_height": max(rep.heights.values(), default=0),
            "heights_audited": rep.heights_audited,
            "txs_submitted": rep.txs_submitted,
            "actions_fired": rep.actions_fired,
            "violations": list(rep.violations),
        })
        for v in rep.violations:
            violations.append({"phase": i, "kind": _violation_kind(v),
                               "detail": str(v),
                               "last_phase": _last_phases(v)})
        if rep.violations:
            repro = rep.repro
            if minimize_on_violation:
                minimized = _minimize_phase(
                    root, spec, phase_seed, nodes, topology, dur,
                    liveness_budget_s, _violation_kind(rep.violations[0]),
                    max_minimize_runs, logger=logger)
            break  # a campaign's job on failure is the minimized repro
        i += 1
    artifact = {
        "version": SCHEMA_VERSION,
        "seed": seed, "budget_s": budget_s, "phase_s": phase_s,
        "nodes": nodes, "topology": topology,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "phases": phases,
        "coverage": {k: coverage[k] for k in sorted(coverage)},
        "stats": {
            "heights_audited": sum(p["heights_audited"] for p in phases),
            "txs_submitted": sum(p["txs_submitted"] for p in phases),
            "actions_fired": sum(p["actions_fired"] for p in phases),
            "max_height": max((p["max_height"] for p in phases), default=0),
        },
        "violations": violations,
        "repro": repro,
        "minimized_repro": minimized,
    }
    if out:
        with open(out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1, sort_keys=False)
            f.write("\n")
    if minimized:
        print(minimized)
    return artifact


def _minimize_phase(root: str, spec: str, seed: int, nodes: int,
                    topology: str, duration_s: float,
                    liveness_budget_s: float, signature: str,
                    max_runs: int, logger=None) -> str:
    """Delta-debug a failing phase schedule against its violation KIND.
    Each probe re-runs a seeded soak over the entry subset in a fresh
    home; probe durations shrink with the subset (last action time plus a
    detection window), so minimization gets cheaper as it converges."""
    probes = {"n": 0}

    def run_fn(entries: list[str]) -> bool:
        probes["n"] += 1
        sub = ";".join(entries)
        last_at = max((SoakAction.parse(e).at_s for e in entries),
                      default=0.0)
        dur = min(duration_s, last_at + liveness_budget_s + 12.0)
        try:
            rep = _run_phase(
                os.path.join(root, f"minimize_{probes['n']:02d}"), sub,
                seed, nodes, topology, dur, liveness_budget_s,
                logger=logger)
        except Exception:  # noqa: BLE001 - a probe that cannot even run
            return False   # does not reproduce the recorded signature
        return any(_violation_kind(v) == signature for v in rep.violations)

    entries = [e for e in spec.split(";") if e.strip()]
    subset, runs = minimize(entries, run_fn, max_runs=max_runs)
    if logger:
        logger.info("campaign minimized", entries=len(entries),
                    kept=len(subset), probes=runs)
    return repro_line(seed, nodes, topology, duration_s,
                      ";".join(subset), durable=True)


def main(argv=None) -> int:
    import argparse
    import tempfile

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S)
    ap.add_argument("--phase", type=float, default=DEFAULT_PHASE_S)
    ap.add_argument("--nodes", type=int, default=DEFAULT_NODES)
    ap.add_argument("--out", default="SOAK_r01.json")
    args = ap.parse_args(argv)
    with tempfile.TemporaryDirectory(prefix="tmtpu-campaign-") as root:
        artifact = run_campaign(root, seed=args.seed, budget_s=args.budget,
                                phase_s=args.phase, nodes=args.nodes,
                                out=args.out)
    print(json.dumps(artifact["stats"], indent=1))
    print(f"coverage: {sorted(artifact['coverage'])}")
    return 0 if not artifact["violations"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
