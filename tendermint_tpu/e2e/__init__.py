"""Manifest-driven e2e testnet runner (reference: test/e2e/)."""

from tendermint_tpu.e2e.runner import Manifest, Perturbation, Runner

__all__ = ["Manifest", "Perturbation", "Runner"]
