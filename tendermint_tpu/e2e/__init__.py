"""Manifest-driven e2e testnet runner (reference: test/e2e/) plus the
in-process scenario fabric and seeded soak harness (docs/SOAK.md).

Heavy imports stay lazy: `fabric` and `soak` pull in node/consensus; the
package import must stay cheap for the CLI."""

from tendermint_tpu.e2e.runner import (
    Manifest,
    Perturbation,
    PowerChange,
    Runner,
)

__all__ = ["Manifest", "Perturbation", "PowerChange", "Runner"]
