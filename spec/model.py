"""Executable bounded model of the Tendermint consensus voting rules.

The reference ships mechanized safety proofs (spec/ivy-proofs/
accountable_safety_1.ivy, checked by Ivy). This module is the executable
analogue for THIS implementation: a small-scope model of the vote/lock
discipline written as pure functions, plus an exhaustive asynchronous
scheduler that explores EVERY reachable interleaving at a bounded scope
(N validators, R rounds, two candidate values) and checks:

 * agreement   — no two honest validators decide different values
               (spec/consensus.md "Theorem (no two commits)");
 * teeth       — with the lock rule deliberately removed, or with f >= N/3,
               the checker FINDS a disagreement trace (the invariant is
               not vacuous);
 * accountability — in every fork trace found at f >= N/3, blame
               localizes: at least f+1 validators signed provably
               contradictory votes, and every blamed validator is
               actually byzantine (spec/consensus.md "Accountability").

The model covers Algorithm 1 of the Tendermint paper at the granularity
the safety argument needs: proposals with POL rounds, prevote/precommit
thresholds, lock/unlock via later-round polkas, nil votes and round
skipping. Timeouts are modeled as always-enabled nil paths (asynchrony =
the scheduler may fire them whenever their guard holds). Byzantine
validators "flood": every possible vote of theirs exists in the message
soup from the start — the worst case, and it removes adversary choice
from the search. Asynchrony is the honest validators' nondeterministic
choice of which enabled rule to fire next; the soup is monotone, so
exploring all rule interleavings covers all delivery schedules.

Code mapping: the modeled rules are the ones consensus/state_machine.py
implements — _do_prevote's lock check, _enter_precommit's polka handling
(lock set/move/unlock), _is_proposal_complete's pol_round evidence check,
and VoteSet 2/3 thresholds (types/vote_set.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

NIL = "-"
VALUES = ("A", "B")

PROPOSE, PREVOTE_STEP, PRECOMMIT_STEP, DONE = "P", "V", "C", "D"


@dataclass(frozen=True)
class Vote:
    """A signed vote statement: (round, type, value, voter)."""

    round: int
    type: str  # "prevote" | "precommit"
    value: str  # "A" | "B" | NIL
    voter: int


@dataclass(frozen=True)
class Proposal:
    round: int
    value: str
    pol_round: int  # -1 = fresh proposal


@dataclass(frozen=True)
class HonestState:
    round: int = 0
    step: str = PROPOSE
    locked_value: str = NIL
    locked_round: int = -1
    # valid_value/valid_round are not modeled: their only consumer is the
    # proposer's choice, and the proposal space already contains every
    # (value, pol_round) a proposer could send (see proposals()).
    decided: str = NIL


@dataclass
class Config:
    n_honest: int = 3
    n_byz: int = 1
    max_round: int = 1  # rounds 0..max_round inclusive
    lock_rule: bool = True  # teeth: set False to break R4/R5
    quorum: int | None = None  # default = the reference's >2/3 rule

    def __post_init__(self):
        n = self.n_honest + self.n_byz
        if self.quorum is None:
            # strictly more than 2/3 of total power (types/vote_set.py
            # two-thirds majority; equal unit powers here)
            self.quorum = (2 * n) // 3 + 1

    @property
    def n(self) -> int:
        return self.n_honest + self.n_byz


def byzantine_soup(cfg: Config) -> frozenset[Vote]:
    """Every vote a byzantine validator could ever sign (flooding)."""
    soup = set()
    for voter in range(cfg.n_honest, cfg.n):
        for r in range(cfg.max_round + 1):
            for t in ("prevote", "precommit"):
                for v in (*VALUES, NIL):
                    soup.add(Vote(r, t, v, voter))
    return frozenset(soup)


def proposals(cfg: Config) -> tuple[Proposal, ...]:
    """The proposal space: in each round, a proposal for each value with
    each admissible POL round. Honest proposers are subsumed: whatever an
    honest proposer would send exists here, and the PREVOTE rule guards
    acceptance with the POL evidence check, so extra (byzantine) proposals
    can only add behaviors, never hide a violation of the vote rules."""
    out = []
    for r in range(cfg.max_round + 1):
        for v in VALUES:
            for pol in range(-1, r):
                out.append(Proposal(r, v, pol))
    return tuple(out)


def count(votes: frozenset[Vote], r: int, t: str, v: str | None) -> int:
    """Voting power (1 each) for (round, type, value); value None = any,
    counting DISTINCT voters (an equivocator contributes 1 to the any-vote
    tally, exactly like types/vote_set.py sum-of-powers semantics)."""
    if v is None:
        return len({x.voter for x in votes if x.round == r and x.type == t})
    return sum(1 for x in votes
               if x.round == r and x.type == t and x.value == v)


# ---------------------------------------------------------------------------
# The transition relation: all enabled (validator, action) pairs.
# Each action returns (new_state, new_votes_to_send).
# ---------------------------------------------------------------------------


def enabled_actions(cfg: Config, soup: frozenset[Vote],
                    props: tuple[Proposal, ...], me: int, s: HonestState):
    """Yield (label, new_state, sent_votes) for every rule instance honest
    validator `me` may fire in the current message soup."""
    if s.decided != NIL:
        return
    q = cfg.quorum
    r = s.round

    if s.step == PROPOSE:
        # upon PROPOSAL(r, v, -1): prevote v iff lock allows
        # (state_machine.py _do_prevote; Algorithm 1 line 22).
        for p in props:
            if p.round != r or p.pol_round != -1:
                continue
            ok = (not cfg.lock_rule or s.locked_round == -1
                  or s.locked_value == p.value)
            vote = p.value if ok else NIL
            yield (f"prevote{r}:{vote}",
                   replace(s, step=PREVOTE_STEP),
                   (Vote(r, "prevote", vote, me),))
        # upon PROPOSAL(r, v, vr) + 2f+1 PREVOTE(vr, v), vr < r
        # (Algorithm 1 line 28; _is_proposal_complete POL evidence).
        for p in props:
            if p.round != r or p.pol_round < 0:
                continue
            if count(soup, p.pol_round, "prevote", p.value) < q:
                continue
            ok = (not cfg.lock_rule or s.locked_round <= p.pol_round
                  or s.locked_value == p.value)
            vote = p.value if ok else NIL
            yield (f"prevote{r}:{vote}(pol{p.pol_round})",
                   replace(s, step=PREVOTE_STEP),
                   (Vote(r, "prevote", vote, me),))
        # timeout_propose: prevote nil (Algorithm 1 line 57).
        yield (f"prevote{r}:nil(timeout)",
               replace(s, step=PREVOTE_STEP),
               (Vote(r, "prevote", NIL, me),))

    elif s.step == PREVOTE_STEP:
        # upon 2f+1 PREVOTE(r, v): lock + precommit v
        # (Algorithm 1 line 36; _enter_precommit polka path).
        for v in VALUES:
            if count(soup, r, "prevote", v) < q:
                continue
            ns = replace(s, step=PRECOMMIT_STEP)
            if cfg.lock_rule:
                ns = replace(ns, locked_value=v, locked_round=r)
            yield (f"precommit{r}:{v}", ns, (Vote(r, "precommit", v, me),))
        # upon 2f+1 PREVOTE(r, nil): precommit nil (line 44). A nil polka
        # at a round above the lock releases it (_enter_precommit:782-785).
        if count(soup, r, "prevote", NIL) >= q:
            ns = replace(s, step=PRECOMMIT_STEP)
            if cfg.lock_rule and s.locked_round < r:
                ns = replace(ns, locked_value=NIL, locked_round=-1)
            yield (f"precommit{r}:nil", ns, (Vote(r, "precommit", NIL, me),))
        # timeout_prevote after 2f+1 any prevotes: precommit nil (line 61).
        if count(soup, r, "prevote", None) >= q:
            yield (f"precommit{r}:nil(timeout)",
                   replace(s, step=PRECOMMIT_STEP),
                   (Vote(r, "precommit", NIL, me),))

    elif s.step == PRECOMMIT_STEP:
        # timeout_precommit after 2f+1 any precommits: next round (line 65).
        if r < cfg.max_round and count(soup, r, "precommit", None) >= q:
            yield (f"round{r + 1}", replace(s, round=r + 1, step=PROPOSE), ())

    # upon 2f+1 PRECOMMIT(r', v) at ANY time: decide v (line 49).
    for rr in range(cfg.max_round + 1):
        for v in VALUES:
            if count(soup, rr, "precommit", v) >= q:
                yield (f"decide:{v}@{rr}",
                       replace(s, decided=v, step=DONE), ())


# ---------------------------------------------------------------------------
# Exhaustive DFS over all interleavings, memoized on global state.
# ---------------------------------------------------------------------------


@dataclass
class Result:
    states: int = 0
    violation: tuple | None = None  # first (trace, honest_states) found
    violations: list = field(default_factory=list)  # ALL violating states
    lemma1_violation: tuple | None = None  # (round, soup) with two polkas
    decisions_seen: set = field(default_factory=set)


def explore(cfg: Config, max_states: int = 2_000_000,
            stop_at_violation: bool = False) -> Result:
    """DFS every reachable configuration; record the first agreement
    violation (two honest validators decided differently) with its trace.

    When f < N/3 the one-polka-per-round lemma (spec/consensus.md Lemma 1)
    is also checked at every reached state. `stop_at_violation` aborts the
    search at the first agreement violation (for the teeth checks, where
    one witness trace suffices)."""
    props = proposals(cfg)
    byz = byzantine_soup(cfg)
    check_lemma1 = cfg.n_byz * 3 < cfg.n
    init = (tuple(HonestState() for _ in range(cfg.n_honest)), frozenset())
    seen = set()
    res = Result()
    stack = [(init, ())]
    while stack:
        (honest, sent), trace = stack.pop()
        if (honest, sent) in seen:
            continue
        seen.add((honest, sent))
        res.states += 1
        if res.states > max_states:
            raise RuntimeError(f"state budget exceeded ({max_states})")
        decided = [s.decided for s in honest if s.decided != NIL]
        res.decisions_seen.update(decided)
        if len(set(decided)) > 1:
            if res.violation is None:
                res.violation = (trace, honest)
            res.violations.append((trace, honest))
            if stop_at_violation:
                return res
            continue  # no need to extend a violating trace
        soup = byz | sent
        if check_lemma1 and res.lemma1_violation is None:
            for r in range(cfg.max_round + 1):
                polkas = [v for v in VALUES
                          if count(soup, r, "prevote", v) >= cfg.quorum]
                if len(polkas) > 1:
                    res.lemma1_violation = (r, soup)
        for i, s in enumerate(honest):
            for label, ns, out in enabled_actions(cfg, soup, props, i, s):
                nh = tuple(ns if j == i else h for j, h in enumerate(honest))
                nsent = sent | frozenset(out)
                if (nh, nsent) not in seen:
                    stack.append(((nh, nsent), trace + ((i, label),)))
    return res


# ---------------------------------------------------------------------------
# Accountability over a fork trace.
# ---------------------------------------------------------------------------


def fork_blame(cfg: Config, trace, honest) -> set[int]:
    """Given a violating trace, rebuild every vote each validator signed
    (honest from the trace, byzantine = the flood) and return the
    validators holding provably contradictory signatures: two votes at one
    (round, type) for different values — the DuplicateVoteEvidence shape
    (types/vote_set.py conflict detection; evidence/pool.py
    _verify_duplicate_vote).

    The claim this checks, over EVERY fork the explorer can produce: blame
    always localizes to >= f+1 validators and NEVER touches an honest one
    (honest rule-followers cast at most one vote per (round, type) by
    construction of the step machine). The byzantine flood signs
    everything, so byzantine signers carry contradictions by definition —
    the load-bearing assertion is the honest side."""
    sent: dict[tuple[int, int, str], set[str]] = {}
    for i, label in trace:
        if label.startswith("prevote") or label.startswith("precommit"):
            t = "prevote" if label.startswith("prevote") else "precommit"
            r = int(label[len(t):label.index(":")])
            v = label.split(":", 1)[1].split("(", 1)[0]
            v = NIL if v == "nil" else v
            sent.setdefault((i, r, t), set()).add(v)
    # Byzantine flood: everything signed (same soup explore() used).
    for vt in byzantine_soup(cfg):
        sent.setdefault((vt.voter, vt.round, vt.type), set()).add(vt.value)
    blamed = set()
    for (voter, _r, _t), vals in sent.items():
        concrete = vals - {NIL}
        if len(concrete) > 1 or (concrete and NIL in vals):
            blamed.add(voter)
    return blamed
