"""Executable bounded model of the Tendermint consensus voting rules.

The reference ships mechanized safety proofs (spec/ivy-proofs/
accountable_safety_1.ivy, checked by Ivy). This module is the executable
analogue for THIS implementation: a small-scope model of the vote/lock
discipline written as pure functions, plus an exhaustive asynchronous
scheduler that explores EVERY reachable interleaving at a bounded scope
(N validators, R rounds, two candidate values) and checks:

 * agreement   — no two honest validators decide different values
               (spec/consensus.md "Theorem (no two commits)");
 * teeth       — with the lock rule deliberately removed, or with f >= N/3,
               the checker FINDS a disagreement trace (the invariant is
               not vacuous);
 * accountability — in every fork trace found at f >= N/3, blame
               localizes: at least f+1 validators signed provably
               contradictory votes, and every blamed validator is
               actually byzantine (spec/consensus.md "Accountability").

The model covers the IMPLEMENTATION's voting rules (v0.34 semantics,
which differ from the Tendermint paper's Algorithm 1 at the prevote
step): a locked validator always prevotes its locked block
(defaultDoPrevote, reference consensus/state.go:1256); locks move or
release only at the precommit step on a polka (relock, lock-move, unlock
on a nil polka or on a polka for an unfetched block, state.go:1320-1440).
The r5 scope increase to three rounds earns its keep here: the
prevote-lock discipline is exactly what makes unlock-on-nil-polka safe,
and weakening it (lock_rule="amnesia") yields a fork the explorer finds
only at max_round >= 2 — lock at round 0, amnesiac nil polka at round 1
releases it, conflicting polka and commit at round 2. Timeouts are modeled as always-enabled nil paths (asynchrony =
the scheduler may fire them whenever their guard holds). Byzantine
validators "flood": every possible vote of theirs exists in the message
soup from the start — the worst case, and it removes adversary choice
from the search. Asynchrony is the honest validators' nondeterministic
choice of which enabled rule to fire next; the soup is monotone, so
exploring all rule interleavings covers all delivery schedules.

Code mapping: consensus/state_machine.py _do_prevote (locked-block
prevote), _enter_precommit (relock / lock-move / unlock paths), and
VoteSet 2/3 thresholds (types/vote_set.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

NIL = "-"
VALUES = ("A", "B")

PROPOSE, PREVOTE_STEP, PRECOMMIT_STEP, DONE = "P", "V", "C", "D"


@dataclass(frozen=True)
class Vote:
    """A signed vote statement: (round, type, value, voter)."""

    round: int
    type: str  # "prevote" | "precommit"
    value: str  # "A" | "B" | NIL
    voter: int


@dataclass(frozen=True)
class Proposal:
    round: int
    value: str
    pol_round: int  # -1 = fresh proposal


@dataclass(frozen=True)
class HonestState:
    round: int = 0
    step: str = PROPOSE
    locked_value: str = NIL
    locked_round: int = -1
    # valid_value/valid_round are not modeled: their only consumer is the
    # proposer's choice, and the proposal space already contains every
    # (value, pol_round) a proposer could send (see proposals()).
    decided: str = NIL


@dataclass
class Config:
    n_honest: int = 3
    n_byz: int = 1
    max_round: int = 1  # rounds 0..max_round inclusive
    # lock discipline knob (teeth scenarios):
    #   True      — the implementation's rules (defaultDoPrevote: locked ->
    #               prevote the locked block, always)
    #   False     — no lock at all (prevote anything, precommit any polka)
    #   "amnesia" — locks are kept at the precommit step but a locked
    #               validator may prevote nil on timeout, "forgetting" its
    #               lock at the prevote step. Combined with the reference's
    #               unlock-on-nil-polka this forks — and the fork needs
    #               three rounds (lock@0, amnesiac nil polka@1 unlocks,
    #               conflicting polka+commit@2), which is exactly why the
    #               r5 scope increase to max_round=2 has teeth.
    lock_rule: object = True
    quorum: int | None = None  # default = the reference's >2/3 rule
    # per-validator voting powers (honest first, then byzantine); None =
    # unit powers. Quorum defaults to >2/3 of TOTAL power either way
    # (types/validator_set.py total_voting_power semantics).
    powers: tuple | None = None
    # Model decisions as explicit transitions (needed for trace-level blame
    # analysis). For pure safety sweeps set False: the DECIDE action sends
    # nothing and halts its validator, and its guard is monotone in the
    # soup, so "two honest decide differently" is reachable IFF a state
    # with two conflicting precommit quorums is — which explore() then
    # checks as a state predicate instead. Cuts the explored space hard
    # (every quorum otherwise spawns decide successors per validator).
    decide_actions: bool = True

    def __post_init__(self):
        n = self.n_honest + self.n_byz
        if self.powers is not None and len(self.powers) != n:
            raise ValueError("powers must cover every validator")
        if self.quorum is None:
            self.quorum = (2 * self.total_power) // 3 + 1

    @property
    def n(self) -> int:
        return self.n_honest + self.n_byz

    @property
    def total_power(self) -> int:
        return sum(self.powers) if self.powers is not None else self.n

    def power(self, voter: int) -> int:
        return self.powers[voter] if self.powers is not None else 1

    @property
    def byz_power(self) -> int:
        return sum(self.power(i) for i in range(self.n_honest, self.n))


def byzantine_soup(cfg: Config) -> frozenset[Vote]:
    """Every vote a byzantine validator could ever sign (flooding)."""
    soup = set()
    for voter in range(cfg.n_honest, cfg.n):
        for r in range(cfg.max_round + 1):
            for t in ("prevote", "precommit"):
                for v in (*VALUES, NIL):
                    soup.add(Vote(r, t, v, voter))
    return frozenset(soup)


def proposals(cfg: Config) -> tuple[Proposal, ...]:
    """The proposal space: in each round, a proposal for each value with
    each admissible POL round. Honest proposers are subsumed: whatever an
    honest proposer would send exists here, and the PREVOTE rule guards
    acceptance with the POL evidence check, so extra (byzantine) proposals
    can only add behaviors, never hide a violation of the vote rules."""
    out = []
    for r in range(cfg.max_round + 1):
        for v in VALUES:
            for pol in range(-1, r):
                out.append(Proposal(r, v, pol))
    return tuple(out)


def count(cfg: Config, votes: frozenset[Vote], r: int, t: str,
          v: str | None) -> int:
    """Voting power for (round, type, value); value None = any, counting
    DISTINCT voters (an equivocator contributes once to the any-vote
    tally, exactly like types/vote_set.py sum-of-powers semantics)."""
    if v is None:
        return sum(cfg.power(w) for w in
                   {x.voter for x in votes if x.round == r and x.type == t})
    return sum(cfg.power(x.voter) for x in votes
               if x.round == r and x.type == t and x.value == v)


def tally_soup(cfg: Config, soup: frozenset) -> dict:
    """One pass over the soup -> {(r, t, v): power, (r, t, None): distinct-
    voter power}. explore() computes this once per state instead of letting
    every rule instance rescan the soup."""
    tl: dict = {}
    anyv: dict = {}
    for x in soup:
        p = cfg.power(x.voter)
        k = (x.round, x.type, x.value)
        tl[k] = tl.get(k, 0) + p
        d = anyv.setdefault((x.round, x.type), {})
        d.setdefault(x.voter, p)
    for (r, t), d in anyv.items():
        tl[(r, t, None)] = sum(d.values())
    return tl


# ---------------------------------------------------------------------------
# The transition relation: all enabled (validator, action) pairs.
# Each action returns (new_state, new_votes_to_send).
# ---------------------------------------------------------------------------


def enabled_actions(cfg: Config, soup: frozenset[Vote],
                    props: tuple[Proposal, ...], me: int, s: HonestState,
                    tl: dict | None = None):
    """Yield (label, new_state, sent_votes) for every rule instance honest
    validator `me` may fire in the current message soup. `tl` is an
    optional precomputed tally_soup(cfg, soup)."""
    if s.decided != NIL:
        return
    if tl is None:
        tl = tally_soup(cfg, soup)
    q = cfg.quorum
    r = s.round

    if s.step == PROPOSE:
        locked = bool(cfg.lock_rule) and s.locked_round >= 0
        if locked and cfg.lock_rule is True:
            # v0.34 defaultDoPrevote (reference consensus/state.go:1256-1259,
            # mirrored by state_machine.py _do_prevote): a locked validator
            # ALWAYS prevotes its locked block — proposals and timeouts
            # change nothing. THIS is what makes the implementation's
            # unlock-on-nil-polka safe: while f+1 honest hold locks on the
            # decided value, no nil polka (and no other polka) can form.
            yield (f"prevote{r}:{s.locked_value}",
                   replace(s, step=PREVOTE_STEP),
                   (Vote(r, "prevote", s.locked_value, me),))
        else:
            if locked:  # cfg.lock_rule == "amnesia"
                # prevote-amnesia bug: the validator still knows its lock
                # (may prevote it) but on timeout "forgets" and prevotes
                # nil like an unlocked one (the removed guard above). The
                # explorer finds the resulting fork — it needs THREE
                # rounds: lock at 0, nil polka at 1 (the amnesiac nil
                # prevotes), unlock, conflicting polka+commit at 2.
                yield (f"prevote{r}:{s.locked_value}",
                       replace(s, step=PREVOTE_STEP),
                       (Vote(r, "prevote", s.locked_value, me),))
            else:
                # unlocked: prevote any current-round proposal...
                for v in sorted({p.value for p in props if p.round == r}):
                    yield (f"prevote{r}:{v}",
                           replace(s, step=PREVOTE_STEP),
                           (Vote(r, "prevote", v, me),))
            # ...or nil on timeout_propose / invalid proposal.
            yield (f"prevote{r}:nil(timeout)",
                   replace(s, step=PREVOTE_STEP),
                   (Vote(r, "prevote", NIL, me),))

    elif s.step == PREVOTE_STEP:
        # enterPrecommit (reference consensus/state.go:1320-1440, mirrored
        # by _enter_precommit): on a polka for v — relock if already locked
        # on v; else either move the lock and precommit v (validator has
        # the block) or unlock and precommit nil (polka for a block it
        # does not have; the polka itself is the POL for the unlock).
        for v in VALUES:
            if tl.get((r, "prevote", v), 0) < q:
                continue
            if not cfg.lock_rule:
                yield (f"precommit{r}:{v}",
                       replace(s, step=PRECOMMIT_STEP),
                       (Vote(r, "precommit", v, me),))
                continue
            if s.locked_value == v:
                yield (f"precommit{r}:{v}",
                       replace(s, step=PRECOMMIT_STEP, locked_round=r),
                       (Vote(r, "precommit", v, me),))
            else:
                yield (f"precommit{r}:{v}",
                       replace(s, step=PRECOMMIT_STEP,
                               locked_value=v, locked_round=r),
                       (Vote(r, "precommit", v, me),))
                yield (f"precommit{r}:nil(noblock)",
                       replace(s, step=PRECOMMIT_STEP,
                               locked_value=NIL, locked_round=-1),
                       (Vote(r, "precommit", NIL, me),))
        # +2/3 prevoted nil: unlock, precommit nil (state.go:1367-1383).
        if tl.get((r, "prevote", NIL), 0) >= q:
            ns = replace(s, step=PRECOMMIT_STEP)
            if cfg.lock_rule:
                ns = replace(ns, locked_value=NIL, locked_round=-1)
            yield (f"precommit{r}:nil", ns, (Vote(r, "precommit", NIL, me),))
        # timeout_prevote after 2f+1 any prevotes: precommit nil, KEEPING
        # the lock (no polka, no POL to unlock on).
        if tl.get((r, "prevote", None), 0) >= q:
            yield (f"precommit{r}:nil(timeout)",
                   replace(s, step=PRECOMMIT_STEP),
                   (Vote(r, "precommit", NIL, me),))

    elif s.step == PRECOMMIT_STEP:
        # timeout_precommit after 2f+1 any precommits: next round (line 65).
        if r < cfg.max_round and tl.get((r, "precommit", None), 0) >= q:
            yield (f"round{r + 1}", replace(s, round=r + 1, step=PROPOSE), ())

    # upon 2f+1 PRECOMMIT(r', v) at ANY time: decide v (line 49).
    if cfg.decide_actions:
        for rr in range(cfg.max_round + 1):
            for v in VALUES:
                if tl.get((rr, "precommit", v), 0) >= q:
                    yield (f"decide:{v}@{rr}",
                           replace(s, decided=v, step=DONE), ())


# ---------------------------------------------------------------------------
# Exhaustive DFS over all interleavings, memoized on global state.
# ---------------------------------------------------------------------------


@dataclass
class Result:
    states: int = 0
    violation: tuple | None = None  # first (trace, honest_states) found
    violations: list = field(default_factory=list)  # ALL violating states
    lemma1_violation: tuple | None = None  # (round, soup) with two polkas
    decisions_seen: set = field(default_factory=set)


def _state_key(s: HonestState) -> tuple:
    return (s.round, s.step, s.locked_value, s.locked_round, s.decided)


def _canon(honest: tuple, sent: frozenset, n_honest: int):
    """Canonical representative of the honest-permutation orbit.

    Equal-power honest validators are interchangeable: permuting their ids
    (consistently in the state tuple AND the vote soup) is an automorphism
    of the transition system. Sorting by (state, own sent votes) picks one
    representative per orbit exactly — validators with identical keys are
    genuinely indistinguishable, so any further permutation among them
    leaves (honest, sent) invariant. Cuts the explored space up to
    n_honest! without losing any reachable inequivalent configuration."""
    per = [tuple(sorted((v.round, v.type, v.value)
                        for v in sent if v.voter == i))
           for i in range(n_honest)]
    order = sorted(range(n_honest),
                   key=lambda i: (_state_key(honest[i]), per[i]))
    if order == list(range(n_honest)):
        return honest, sent
    relab = {old: new for new, old in enumerate(order)}
    nh = tuple(honest[i] for i in order)
    ns = frozenset(
        Vote(v.round, v.type, v.value, relab.get(v.voter, v.voter))
        for v in sent)
    return nh, ns


def explore(cfg: Config, max_states: int = 2_000_000,
            stop_at_violation: bool = False,
            symmetry_reduce: bool = False) -> Result:
    """DFS every reachable configuration; record the first agreement
    violation (two honest validators decided differently) with its trace.

    When f < N/3 the one-polka-per-round lemma (spec/consensus.md Lemma 1)
    is also checked at every reached state. `stop_at_violation` aborts the
    search at the first agreement violation (for the teeth checks, where
    one witness trace suffices). `symmetry_reduce` merges honest-validator
    permutation orbits (equal powers only); traces then carry relabeled
    validator ids, so blame analysis (fork_blame) should run with the
    reduction OFF."""
    if symmetry_reduce and cfg.powers is not None and len(
            set(cfg.powers[:cfg.n_honest])) > 1:
        raise ValueError("symmetry reduction requires equal honest powers")
    props = proposals(cfg)
    byz = byzantine_soup(cfg)
    check_lemma1 = cfg.byz_power * 3 < cfg.total_power
    init = (tuple(HonestState() for _ in range(cfg.n_honest)), frozenset())
    seen = set()
    res = Result()
    stack = [(init, ())]
    while stack:
        (honest, sent), trace = stack.pop()
        if symmetry_reduce:
            honest, sent = _canon(honest, sent, cfg.n_honest)
        if (honest, sent) in seen:
            continue
        seen.add((honest, sent))
        res.states += 1
        if res.states > max_states:
            raise RuntimeError(f"state budget exceeded ({max_states})")
        soup = byz | sent
        tl = tally_soup(cfg, soup)
        if cfg.decide_actions:
            decided = [s.decided for s in honest if s.decided != NIL]
            res.decisions_seen.update(decided)
            if len(set(decided)) > 1:
                if res.violation is None:
                    res.violation = (trace, honest)
                res.violations.append((trace, honest))
                if stop_at_violation:
                    return res
                continue  # no need to extend a violating trace
        else:
            # decide-free mode: "two honest decide differently" reachable
            # IFF two conflicting precommit quorums coexist (see Config).
            committed = {v for rr in range(cfg.max_round + 1)
                         for v in VALUES
                         if tl.get((rr, "precommit", v), 0) >= cfg.quorum}
            res.decisions_seen.update(committed)
            if len(committed) > 1:
                if res.violation is None:
                    res.violation = (trace, honest)
                res.violations.append((trace, honest))
                if stop_at_violation:
                    return res
                continue
        if check_lemma1 and res.lemma1_violation is None:
            for r in range(cfg.max_round + 1):
                polkas = [v for v in VALUES
                          if tl.get((r, "prevote", v), 0) >= cfg.quorum]
                if len(polkas) > 1:
                    res.lemma1_violation = (r, soup)
        for i, s in enumerate(honest):
            for label, ns, out in enabled_actions(cfg, soup, props, i, s, tl):
                nh = tuple(ns if j == i else h for j, h in enumerate(honest))
                nsent = sent | frozenset(out)
                if (nh, nsent) not in seen:
                    stack.append(((nh, nsent), trace + ((i, label),)))
    return res


# ---------------------------------------------------------------------------
# Accountability over a fork trace.
# ---------------------------------------------------------------------------


def fork_blame(cfg: Config, trace, honest) -> set[int]:
    """Given a violating trace, rebuild every vote each validator signed
    (honest from the trace, byzantine = the flood) and return the
    validators holding provably contradictory signatures: two votes at one
    (round, type) for different values — the DuplicateVoteEvidence shape
    (types/vote_set.py conflict detection; evidence/pool.py
    _verify_duplicate_vote).

    The claim this checks, over EVERY fork the explorer can produce: blame
    always localizes to >= f+1 validators and NEVER touches an honest one
    (honest rule-followers cast at most one vote per (round, type) by
    construction of the step machine). The byzantine flood signs
    everything, so byzantine signers carry contradictions by definition —
    the load-bearing assertion is the honest side."""
    sent: dict[tuple[int, int, str], set[str]] = {}
    for i, label in trace:
        if label.startswith("prevote") or label.startswith("precommit"):
            t = "prevote" if label.startswith("prevote") else "precommit"
            r = int(label[len(t):label.index(":")])
            v = label.split(":", 1)[1].split("(", 1)[0]
            v = NIL if v == "nil" else v
            sent.setdefault((i, r, t), set()).add(v)
    # Byzantine flood: everything signed (same soup explore() used).
    for vt in byzantine_soup(cfg):
        sent.setdefault((vt.voter, vt.round, vt.type), set()).add(vt.value)
    blamed = set()
    for (voter, _r, _t), vals in sent.items():
        concrete = vals - {NIL}
        if len(concrete) > 1 or (concrete and NIL in vals):
            blamed.add(voter)
    return blamed


# ---------------------------------------------------------------------------
# Bounded liveness under synchrony.
# ---------------------------------------------------------------------------


def synchronous_run(cfg: Config, value: str = "A",
                    withhold_round0: bool = False) -> tuple[int, frozenset]:
    """Deterministic post-GST schedule: every honest validator sees the full
    soup and fires the most progress-making enabled rule each step (prefer
    value prevotes/precommits and decisions over nil/timeout paths).
    Returns (rounds needed until ALL honest decided `value`, final soup);
    raises if the round budget runs out — the bounded-liveness claim
    (spec/consensus.md termination under synchrony with a correct
    proposer).  withhold_round0 models a faulty round-0 proposer: honest
    validators time out, skip the round, and round 1 must decide."""
    props = tuple(p for p in proposals(cfg)
                  if p.value == value or p.pol_round >= 0 or withhold_round0)
    byz = byzantine_soup(cfg)
    honest = [HonestState() for _ in range(cfg.n_honest)]
    sent: set[Vote] = set()

    def pick(me: int, s: HonestState):
        best = None
        usable = tuple(p for p in props
                       if not (withhold_round0 and p.round == 0))
        for act in enabled_actions(cfg, frozenset(sent) | byz, usable, me, s):
            label = act[0]
            rank = (2 if label.startswith("decide:" + value)
                    else 1 if (":" + value) in label
                    else 0)
            if best is None or rank > best[0]:
                best = (rank, act)
        return None if best is None else best[1]

    for _step in range(cfg.n_honest * (cfg.max_round + 1) * 8):
        progressed = False
        for i in range(cfg.n_honest):
            if honest[i].decided != NIL:
                continue
            act = pick(i, honest[i])
            if act is None:
                continue
            _label, ns, out = act
            honest[i] = ns
            sent.update(out)
            progressed = True
        if all(s.decided == value for s in honest):
            return max(s.round for s in honest), frozenset(sent)
        if not progressed:
            break
    raise AssertionError(
        f"liveness: honest validators failed to decide {value} within the "
        f"round budget; states={honest}")
